"""Fleet-telemetry CI smoke (``make telemetry-smoke``, < 60 s).

Stands up a 2-replica serving fleet behind the router, points a
:class:`~instaslice_tpu.obs.telemetry.FleetAggregator` at it (pinned
clock — burn windows advance deterministically), and proves the three
contracts docs/OBSERVABILITY.md "Fleet telemetry" promises:

1. **Exact three-way reconciliation** — the aggregator's federated
   rollups (requests, tokens, per-class SLO attainment) equal the
   loadgen CLIENT-side report equal the journal/metrics counters.
   Not approximately: the clean tenant's TTFT target (30 s) cannot
   miss and the burn tenant's (0.1 ms) cannot be met, so attainment
   is exactly 1.0 / 0.0 on BOTH sides of the wire and any drift is a
   counting bug, not jitter.
2. **Burn-rate lifecycle** — the injected-latency arm (a tenant whose
   TTFT SLO cannot be met) drives the multi-window burn monitor to
   ``SLOBurnRateHigh``; sliding the pinned clock past every window
   with no new misses drives it to ``SLOBurnRateCleared``. Both land
   in the journal.
3. **Cross-process trace stitching** — a routed serving request
   (router → replica) and a capacity-blocked pod grant (controller,
   carrying the serving trace id in its caused-by annotation) stitch
   into ONE timeline with >= 3 components.

The whole scenario runs twice: clean, and under one seeded fault plan
(delay-kind injections only — latency chaos must not change any
counter, so the reconciliation stays exact under faults). Zero hung
requests everywhere.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # run as tools/telemetry_smoke.py
    sys.path.insert(0, REPO)

#: one tenant per class so per-class (server) and per-tenant (client)
#: attainment are the same number — the exactness trick
TENANTS = "steady:1:standard:30,edge:1:latency:0.0001"


def check(cond: bool, msg: str, **ctx) -> None:
    if not cond:
        raise AssertionError(
            f"{msg}" + (f" | {json.dumps(ctx, default=str)}" if ctx
                        else "")
        )


def wait_ready(url: str, timeout: float = 15.0) -> None:
    import threading
    import urllib.error
    import urllib.request

    pacer = threading.Event()
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=2) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        pacer.wait(0.1)
    raise AssertionError(f"{url} never became ready")


def run_loadgen(url: str, tenants: str, requests: int) -> dict:
    from instaslice_tpu.serving import loadgen

    report = loadgen.run(
        url, requests=requests, concurrency=2, prompt_len=4,
        max_tokens=6, vocab=64, stream=True, timeout=60,
        tenants=tenants,
    )
    check(report["outcomes"]["hung"] == 0, "hung requests",
          outcomes=report["outcomes"])
    # hedges/retries would double-count server-side; delay-only fault
    # plans must never trigger them, or exactness is meaningless
    check(report["outcomes"]["hedged-ok"] == 0, "unexpected hedge",
          outcomes=report["outcomes"])
    check(report["ok"] == requests, "not every request succeeded",
          report={k: report[k] for k in ("ok", "outcomes", "errors")})
    return report


def stitched_trace_arm(router_url: str, agg) -> str:
    """Route one traced request through the fleet, then grant a
    capacity-blocked pod carrying that trace id in its caused-by
    annotation. Returns the serving trace id; the caller asserts the
    stitched timeline."""
    from instaslice_tpu.api.constants import CAUSED_BY_ANNOTATION
    from instaslice_tpu.serving.loadgen import _one_request
    from instaslice_tpu.sim import SimCluster
    from instaslice_tpu.utils.trace import new_trace_id

    tid = new_trace_id()
    _, _, toks, err, _ = _one_request(
        router_url, [1, 2, 3], 4, stream=False, timeout=60,
        trace_id=tid,
    )
    check(err is None, "traced request failed", error=err)
    check(toks > 0, "traced request returned no tokens")
    agg.poll()  # capture router.route + serve.* before ring churn

    with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
        # a v5e node is 2x4 = 8 chips: two 2x2 fillers exhaust it
        c.submit("filler-a", "v5e-2x2")
        c.submit("filler-b", "v5e-2x2")
        check(c.wait_phase("filler-a", "Running", timeout=30)
              and c.wait_phase("filler-b", "Running", timeout=30),
              "filler pods never ran")
        c.submit("blocked", "v5e-1x1",
                 annotations={CAUSED_BY_ANNOTATION: tid})
        # the pod must actually WAIT on capacity (the demand the
        # caused-by link records), then get unblocked by a teardown
        time.sleep(0.5)
        check(not c.wait_phase("blocked", "Running", timeout=0.1),
              "blocked pod ran with the node full — not blocked")
        c.delete_pod("filler-a")
        check(c.wait_gone("filler-a", timeout=30),
              "filler never tore down")
        check(c.wait_phase("blocked", "Running", timeout=30),
              "blocked pod never granted after capacity freed")
        c.delete_pod("blocked")
        c.delete_pod("filler-b")
        c.wait_gone("blocked", timeout=30)
        c.wait_gone("filler-b", timeout=30)
    agg.poll()  # capture controller/agent spans + lifecycle events
    return tid


def run_scenario(label: str, fault_plan=None) -> dict:
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.api.constants import (
        REASON_SLO_BURN_CLEARED,
        REASON_SLO_BURN_HIGH,
        REASON_SLO_MISSED,
    )
    from instaslice_tpu.metrics.metrics import FleetMetrics
    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.obs.journal import get_journal
    from instaslice_tpu.obs.telemetry import (
        FleetAggregator,
        TelemetryServer,
        parse_exposition,
    )
    from instaslice_tpu.serving import ServingEngine
    from instaslice_tpu.serving.api_server import ApiServer
    from instaslice_tpu.serving.router import Router

    t_start = time.time()
    cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                      d_ff=64, dtype=jnp.float32, remat=False)
    model = TpuLM(cfg)
    params = model.init(jax.random.key(0))

    def engine() -> ServingEngine:
        return ServingEngine(model, params, max_batch=4, max_len=64,
                             prefill_len=8)

    journal = get_journal()
    base = journal.counts()

    def delta(reason: str) -> int:
        return journal.counts().get(reason, 0) - base.get(reason, 0)

    clk = [time.time()]
    with ApiServer(engine(), block_size=4, tenants=TENANTS,
                   fault_plan=fault_plan) as a, \
            ApiServer(engine(), block_size=4, tenants=TENANTS,
                      fault_plan=fault_plan) as b:
        router = Router(replicas=(a.url, b.url), poll_interval=0.1)
        router.start()
        agg = FleetAggregator(
            router_url=router.url, slo_target=0.99,
            metrics=FleetMetrics(), journal=journal,
            clock=lambda: clk[0],
        )
        tel = TelemetryServer(agg).start()
        try:
            wait_ready(router.url)
            wait_ready(a.url)
            wait_ready(b.url)

            # ---- phase 1: clean traffic, attainment exactly 1.0
            rep_clean = run_loadgen(router.url, "steady:1:standard:30",
                                    8)
            clk[0] += 5
            fleet = agg.poll()
            check(fleet["tokens"] == rep_clean["client_tokens"],
                  "clean: fleet tokens != client tokens",
                  fleet=fleet["tokens"],
                  client=rep_clean["client_tokens"])
            check(fleet["ok_requests"] == rep_clean["ok"],
                  "clean: fleet ok != client ok", fleet=fleet)
            att = fleet["attainment"]["standard"]
            client_att = rep_clean["tenants"]["steady"]["slo_attainment"]
            check(att["attainment"] == 1.0 == client_att,
                  "clean: attainment not exactly 1.0 on both sides",
                  server=att, client=client_att)
            check(not fleet["burn"].get("standard", {}).get("burning"),
                  "clean class burning", burn=fleet["burn"])

            # ---- phase 2: burn traffic in TWO bursts (the monitor
            # needs a miss DELTA between samples), attainment 0.0
            rep_b1 = run_loadgen(router.url, "edge:1:latency:0.0001", 4)
            clk[0] += 60
            fleet = agg.poll()
            check(fleet["tokens"] == rep_clean["client_tokens"]
                  + rep_b1["client_tokens"],
                  "burn1: fleet tokens != sum of client tokens")
            check(fleet["attainment"]["latency"]["attainment"] == 0.0
                  == rep_b1["tenants"]["edge"]["slo_attainment"],
                  "burn1: attainment not exactly 0.0 on both sides",
                  fleet=fleet["attainment"])

            rep_b2 = run_loadgen(router.url, "edge:1:latency:0.0001", 4)
            clk[0] += 60
            fleet = agg.poll()
            burned = rep_b1["ok"] + rep_b2["ok"]
            check(fleet["ok_requests"] == rep_clean["ok"] + burned,
                  "burn2: fleet ok != client ok", fleet=fleet)
            check(fleet["attainment"]["latency"]["missed"] == burned
                  == delta(REASON_SLO_MISSED),
                  "SLO-miss ledger disagrees (fleet vs client vs "
                  "journal)", fleet=fleet["attainment"],
                  journal=delta(REASON_SLO_MISSED))
            check(fleet["burn"]["latency"]["burning"],
                  "burn monitor did not fire", burn=fleet["burn"])
            check(delta(REASON_SLO_BURN_HIGH) == 1,
                  "SLOBurnRateHigh not journaled exactly once",
                  n=delta(REASON_SLO_BURN_HIGH))

            # ---- phase 3: heal — slide past every window, no new
            # misses -> cleared
            clk[0] += 7 * 3600
            fleet = agg.poll()
            check(not fleet["burn"]["latency"]["burning"],
                  "burn did not clear after heal", burn=fleet["burn"])
            check(delta(REASON_SLO_BURN_CLEARED) == 1,
                  "SLOBurnRateCleared not journaled exactly once",
                  n=delta(REASON_SLO_BURN_CLEARED))

            # ---- phase 4: demand->supply stitching + chip-hours
            tid = stitched_trace_arm(router.url, agg)
            timeline = agg.stitcher.timeline(tid)
            check(len(timeline["components"]) >= 3,
                  "stitched timeline spans < 3 components",
                  components=timeline["components"],
                  spans=timeline["spanCount"])
            check(timeline["linked"], "no caused-by linked grant trace",
                  timeline={k: timeline[k] for k in
                            ("components", "spanCount")})
            fleet = agg.poll()
            check(fleet["chip_hours"]["chip_seconds"] > 0,
                  "chip-hours accounting recorded nothing",
                  chip_hours=fleet["chip_hours"])
            check(fleet["chip_hours"]
                  ["chip_hours_per_million_requests"] > 0,
                  "chip-hours per Mreq rollup is zero")

            # ---- the HTTP plane serves what the aggregator knows
            import urllib.request

            with urllib.request.urlopen(tel.url + "/v1/fleet",
                                        timeout=5) as r:
                served = json.loads(r.read())
            check(served["tokens"] == fleet["tokens"],
                  "/v1/fleet drifted from the aggregator")
            with urllib.request.urlopen(
                tel.url + f"/v1/fleet/trace?trace_id={tid}", timeout=5
            ) as r:
                check(json.loads(r.read())["spanCount"]
                      == timeline["spanCount"],
                      "/v1/fleet/trace drifted from the stitcher")
            with urllib.request.urlopen(tel.url + "/metrics",
                                        timeout=5) as r:
                samples = parse_exposition(r.read().decode())
            check(any(n == "tpuslice_fleet_tokens_total"
                      for n, _ in samples),
                  "fleet exposition missing tpuslice_fleet_tokens_total")

            return {
                "arm": label,
                "ok_requests": fleet["ok_requests"],
                "tokens": fleet["tokens"],
                "attainment": fleet["attainment"],
                "chip_seconds": fleet["chip_hours"]["chip_seconds"],
                "stitched_components": timeline["components"],
                "scrape_errors": fleet["scrapes"]["error"],
                "wall_s": round(time.time() - t_start, 1),
            }
        finally:
            tel.stop()
            agg.stop()
            router.stop()


def main() -> int:
    from instaslice_tpu.faults import FaultPlan

    results = []
    results.append(run_scenario("clean"))
    print(json.dumps(results[-1]), flush=True)

    seed = int(os.environ.get("TPUSLICE_TELEMETRY_SEED", "42"))
    plan = (
        FaultPlan(seed)
        .site("engine.decode", probability=0.25, kinds=("delay",),
              delay_s=0.02)
        .site("engine.prefill", probability=0.25, kinds=("delay",),
              delay_s=0.02)
        .site("scheduler.round", probability=0.05, kinds=("delay",),
              delay_s=0.02)
    )
    results.append(run_scenario(f"chaos-seed-{seed}", fault_plan=plan))
    print(json.dumps(results[-1]), flush=True)

    print(json.dumps({"telemetry_smoke": "ok",
                      "arms": [r["arm"] for r in results]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
