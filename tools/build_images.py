"""Build (or maximally prove) the three images, and record how.

The reference's e2e tier runs ``make docker-build`` + ``kind load``
(``/root/reference/test/e2e/e2e_test.go:84-118``,
``test/utils/utils.go:107-116``). This tool:

1. If a container builder (docker / podman / buildah) exists: really
   build all three Dockerfiles and log the digests.
2. Otherwise (this CI image ships none): execute the Dockerfiles' OWN
   build steps directly on the host — the parts that can fail for
   reasons under this repo's control:

   - ``pip``-build the package the ``pip install .`` layers install
     (offline: ``--no-deps --no-build-isolation``; the base image pulls
     deps from PyPI, which this zero-egress host cannot),
   - ``make -C native`` → ``libtpuslice.so`` (the agent/deviceplugin
     in-image native build, same compiler invocation),
   - resolve + import every ENTRYPOINT console script against
     pyproject's ``[project.scripts]``,
   - verify every COPY source path exists in the build context.

   What this cannot prove — base-image pulls, apt installs, PyPI dep
   resolution — is listed explicitly in the log rather than implied.

Writes ``deploy/docker-build.log`` (committed) and exits non-zero on any
failure. Run via ``make build-images`` or directly.
"""

from __future__ import annotations

import datetime
import importlib
import re
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # run as tools/build_images.py
    sys.path.insert(0, str(REPO))
LOG = REPO / "deploy" / "docker-build.log"
DOCKERFILES = {
    "instaslice-tpu/controller": "Dockerfile.controller",
    "instaslice-tpu/agent": "Dockerfile.agent",
    "instaslice-tpu/deviceplugin": "Dockerfile.deviceplugin",
}

lines: list[str] = []


def log(msg: str) -> None:
    print(msg)
    lines.append(msg)


def find_builder() -> str | None:
    for tool in ("docker", "podman", "buildah"):
        if shutil.which(tool):
            return tool
    return None


def real_build(builder: str) -> bool:
    ok = True
    for tag, df in DOCKERFILES.items():
        cmd = [builder, "build", "-t", f"{tag}:dev", "-f", str(REPO / df),
               str(REPO)]
        if builder == "buildah":
            cmd = [builder, "bud", "-t", f"{tag}:dev",
                   "-f", str(REPO / df), str(REPO)]
        log(f"$ {' '.join(cmd)}")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        tail = (proc.stdout + proc.stderr).strip().splitlines()[-12:]
        lines.extend("  " + ln for ln in tail)
        log(f"  -> rc={proc.returncode}")
        ok &= proc.returncode == 0
    return ok


def parse_dockerfile(path: Path):
    """(copy_sources, entrypoint) from a Dockerfile."""
    copies: list[str] = []
    entry = ""
    for raw in path.read_text().splitlines():
        line = raw.strip()
        if line.upper().startswith("COPY ") and "--from=" not in line:
            parts = line.split()[1:]
            copies.extend(p.rstrip("/") for p in parts[:-1])
        elif line.upper().startswith("ENTRYPOINT"):
            m = re.findall(r'"([^"]+)"', line)
            entry = m[0] if m else line.split(None, 1)[1]
    return copies, entry


def load_console_scripts() -> dict:
    import tomllib

    with open(REPO / "pyproject.toml", "rb") as f:
        return tomllib.load(f)["project"].get("scripts", {})


def emulated_build() -> bool:
    ok = True
    scripts = load_console_scripts()

    # 1. the `pip install .` layer: build the wheel offline
    with tempfile.TemporaryDirectory(prefix="imgproof-") as tmp:
        cmd = [sys.executable, "-m", "pip", "wheel", "--no-deps",
               "--no-build-isolation", "-w", tmp, str(REPO)]
        log(f"$ {' '.join(cmd)}")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        wheels = list(Path(tmp).glob("*.whl"))
        if proc.returncode == 0 and wheels:
            log(f"  -> OK: built {wheels[0].name}")
        else:
            log(f"  -> FAIL rc={proc.returncode}: "
                + proc.stderr.strip()[-300:])
            ok = False

    # 2. the in-image native build (agent + deviceplugin layers)
    log("$ make -C native clean all")
    proc = subprocess.run(["make", "-C", str(REPO / "native"), "clean",
                           "all"], capture_output=True, text=True)
    so = REPO / "native" / "build" / "libtpuslice.so"
    if proc.returncode == 0 and so.exists():
        log(f"  -> OK: {so.relative_to(REPO)} "
            f"({so.stat().st_size} bytes)")
    else:
        log(f"  -> FAIL rc={proc.returncode}: {proc.stderr.strip()[-300:]}")
        ok = False

    # 3. per-Dockerfile: COPY sources exist, ENTRYPOINT resolves + imports
    for tag, df in DOCKERFILES.items():
        copies, entry = parse_dockerfile(REPO / df)
        missing = [c for c in copies if not (REPO / c).exists()]
        if missing:
            log(f"{df}: FAIL missing COPY sources {missing}")
            ok = False
        else:
            log(f"{df}: COPY sources exist ({', '.join(copies)})")
        if entry not in scripts:
            log(f"{df}: FAIL entrypoint {entry!r} not in "
                "[project.scripts]")
            ok = False
            continue
        mod, _, fn = scripts[entry].partition(":")
        try:
            m = importlib.import_module(mod)
            getattr(m, fn)
            log(f"{df}: ENTRYPOINT {entry} -> {scripts[entry]} imports OK")
        except Exception as e:  # noqa: BLE001
            log(f"{df}: FAIL entrypoint import: {type(e).__name__}: {e}")
            ok = False

    log("")
    log("NOT provable without a container runtime (recorded, not "
        "implied): base-image pulls (python:3.11-slim), apt-get layers "
        "(g++ make), PyPI dep resolution inside the image "
        "(grpcio/protobuf for the deviceplugin).")
    return ok


def main() -> int:
    stamp = (
        datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )
    log(f"# image build proof — {stamp}")
    builder = find_builder()
    if builder:
        log(f"builder: {builder}")
        ok = real_build(builder)
    else:
        log("builder: NONE (docker/podman/buildah absent in this "
            "environment) — executing the Dockerfiles' build steps "
            "directly instead")
        ok = emulated_build()
    log(f"RESULT: {'PASS' if ok else 'FAIL'}")
    LOG.parent.mkdir(exist_ok=True)
    LOG.write_text("\n".join(lines) + "\n")
    print(f"\nwrote {LOG.relative_to(REPO)}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
