"""Bench-record trend report + regression gate (``make bench-trend``).

The repo records one ``BENCH*_rNN.json`` per perf-bearing PR — a
trajectory, not a point. This tool reads the whole set, groups records
into **tiers** by filename (``BENCH_rNN`` → the grant tier,
``BENCH_SERVING_rNN`` → serving, ``BENCH_SCALE_rNN`` → scale, ...),
prints the headline-metric series (``serve_toks_per_sec``,
``serve_ttft_p95``, grants/sec) in record order, and exits non-zero
when the NEWEST record of any tier regresses more than the threshold
(default 10%) against the best prior record of the same tier — the
"did this PR quietly lose what an earlier PR earned" gate the
fleet-telemetry plane's chip-hours headline will feed.

Direction is inferred from the unit: ``seconds`` is lower-is-better
(grant latency), everything else (tokens/s, grants/sec, fraction) is
higher-is-better. Records that cannot be parsed into a headline value
(truncated early-PR tails) are reported and skipped, never fatal —
history must stay readable even where it is ragged.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

RECORD_RE = re.compile(r"^BENCH(?:_([A-Z]+))?_r(\d+)\.json$")

#: per-record keys echoed into the series report when present
SERIES_KEYS = ("serve_toks_per_sec", "serve_ttft_p95")


def headline(record: dict) -> Optional[Tuple[str, float, str]]:
    """Extract ``(metric, value, unit)`` from one record, tolerating
    every historical shape: the modern ``{"metric", "value", "unit"}``
    form, the scale tier's nested ``scale.grants_per_sec``, and the
    early driver-captured ``{"tail": "...jsonl..."}`` form."""
    d = record
    if "metric" not in d:
        parsed = d.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            d = parsed
        else:
            # early records captured raw stdout; the headline is the
            # last parseable JSON object line carrying "metric"
            for line in reversed(
                (d.get("tail") or "").strip().splitlines()
            ):
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    cand = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict) and "metric" in cand:
                    d = cand
                    break
            else:
                return None
    metric = str(d.get("metric", ""))
    unit = str(d.get("unit", ""))
    value = d.get("value")
    if value is None and isinstance(d.get("scale"), dict):
        value = d["scale"].get("grants_per_sec")
        unit = unit or "grants/sec"
    if value is None:
        return None
    try:
        return metric, float(value), unit
    except (TypeError, ValueError):
        return None


def lower_is_better(unit: str) -> bool:
    return unit == "seconds"


def profile_p95s(record: dict) -> Dict[str, float]:
    """Per-segment p95 ms learned from any ``profile`` block in the
    record (bench.py serving arms emit obs/profiler.py
    ``segment_summary`` under ``"profile"``; the driver may nest arms
    arbitrarily). The keys are DYNAMIC — a segment added by a later PR
    starts gating as soon as two records carry it, without this tool
    changing. When several arms carry profiles, the max per segment is
    kept (conservative)."""
    out: Dict[str, float] = {}

    def walk(d: dict) -> None:
        prof = d.get("profile")
        if isinstance(prof, dict):
            for seg, row in prof.items():
                if not isinstance(row, dict):
                    continue
                try:
                    v = float(row.get("p95Ms"))
                except (TypeError, ValueError):
                    continue
                out[seg] = max(out.get(seg, 0.0), v)
        for v in d.values():
            if isinstance(v, dict):
                walk(v)

    if isinstance(record, dict):
        walk(record)
    return out


def load_records(root: str) -> Dict[str, List[dict]]:
    """``{tier: [entry, ...]}`` in record-number order. Each entry:
    ``{file, n, metric, value, unit, series}`` (value/metric/unit may
    be None when unparsable)."""
    tiers: Dict[str, List[dict]] = {}
    for name in sorted(os.listdir(root)):
        m = RECORD_RE.match(name)
        if not m:
            continue
        tier = m.group(1) or "GRANT"
        try:
            with open(os.path.join(root, name)) as f:
                record = json.load(f)
        except (OSError, json.JSONDecodeError):
            record = {}
        head = headline(record) if isinstance(record, dict) else None
        entry = {
            "file": name,
            "n": int(m.group(2)),
            "metric": head[0] if head else None,
            "value": head[1] if head else None,
            "unit": head[2] if head else None,
            "series": {
                k: record.get(k) for k in SERIES_KEYS
                if isinstance(record, dict) and record.get(k)
                is not None
            },
            "profile_p95": profile_p95s(record),
        }
        tiers.setdefault(tier, []).append(entry)
    for entries in tiers.values():
        entries.sort(key=lambda e: e["n"])
    return tiers


def check_regressions(tiers: Dict[str, List[dict]],
                      threshold: float) -> List[dict]:
    """The gate: for each tier, compare the NEWEST parseable record
    against the best prior parseable record. A >``threshold``
    fractional move in the losing direction is a regression."""
    out = []
    for tier, entries in sorted(tiers.items()):
        parseable = [e for e in entries if e["value"] is not None]
        if len(parseable) < 2:
            continue
        newest = parseable[-1]
        prior = parseable[:-1]
        lower = lower_is_better(newest["unit"] or "")
        best = (min if lower else max)(
            e["value"] for e in prior
        )
        if best == 0:
            continue
        change = (newest["value"] - best) / abs(best)
        regressed = change > threshold if lower \
            else change < -threshold
        if regressed:
            out.append({
                "tier": tier,
                "file": newest["file"],
                "metric": newest["metric"],
                "value": newest["value"],
                "best_prior": best,
                "change_pct": round(change * 100, 2),
            })
    # per-segment round-anatomy gate: a single segment regressing
    # (e.g. host bookkeeping creeping up) must fail the trend even
    # when the headline tok/s hides it behind device-time savings
    for tier, entries in sorted(tiers.items()):
        with_prof = [e for e in entries if e.get("profile_p95")]
        if len(with_prof) < 2:
            continue
        newest = with_prof[-1]
        prior = with_prof[:-1]
        for seg, v in sorted(newest["profile_p95"].items()):
            prior_vals = [e["profile_p95"][seg] for e in prior
                          if seg in e["profile_p95"]]
            if not prior_vals:
                continue   # a NEW segment has no baseline yet
            best = min(prior_vals)
            if best <= 0:
                continue
            change = (v - best) / best
            if change > threshold:
                out.append({
                    "tier": tier,
                    "file": newest["file"],
                    "metric": f"profile_p95.{seg}",
                    "value": v,
                    "best_prior": best,
                    "change_pct": round(change * 100, 2),
                })
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="bench_trend")
    ap.add_argument(
        "--dir",
        default=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        help="directory holding the BENCH_*.json records "
             "(default: the repo root)",
    )
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression gate as a fraction (default "
                         "0.10 = 10%%)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    tiers = load_records(args.dir)
    if not tiers:
        print(f"no BENCH_*_rNN.json records under {args.dir}",
              file=sys.stderr)
        return 1
    regressions = check_regressions(tiers, args.threshold)

    if args.as_json:
        print(json.dumps({
            "tiers": tiers,
            "regressions": regressions,
            "threshold": args.threshold,
        }))
        return 2 if regressions else 0

    for tier, entries in sorted(tiers.items()):
        print(f"tier {tier}:")
        for e in entries:
            if e["value"] is None:
                print(f"  r{e['n']:02d} {e['file']:<24} "
                      f"(no parseable headline; skipped)")
                continue
            extra = "".join(
                f" {k}={v}" for k, v in sorted(e["series"].items())
            )
            print(f"  r{e['n']:02d} {e['file']:<24} "
                  f"{e['metric']}={e['value']:g} {e['unit']}{extra}")
    if regressions:
        print(f"\nREGRESSION (> {args.threshold:.0%} vs best prior "
              "record of the tier):")
        for r in regressions:
            print(f"  {r['tier']}: {r['file']} {r['metric']}="
                  f"{r['value']:g} vs best prior {r['best_prior']:g} "
                  f"({r['change_pct']:+.1f}%)")
        return 2
    print(f"\nno tier regressed > {args.threshold:.0%} "
          "against its best prior record")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
