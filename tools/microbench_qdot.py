"""On-chip microbench for the w8a16 decode matmul paths.

Times three formulations of the decode-critical contraction at serving
shapes (M = batch rows) through :func:`bench_tpu._chained_per_call` —
the RTT-guarded harness (auto-scaled chain of truly data-dependent
steps, one dispatch, one readback, in-phase RTT subtraction):

- ``bf16``: dot against pre-dequantized bf16 weights — what XLA's
  hoisted-dequant decode path streams per step (the bandwidth floor to
  beat: 2 bytes/param/step);
- ``dequant``: int8 weights dequantized inside the step body — the
  dequant is loop-invariant, so this lane measures WHATEVER XLA
  chooses: hoist it (then it equals the bf16 lane — observed for the
  16 MB attn_proj) or keep it fused in-loop (then it approaches the
  int8 roofline — observed for the 84 MB ffn mats). A window into
  XLA's policy, not a fixed formulation;
- ``kernel``: the pallas w8a16 kernel (``ops/quant_matmul.py``) — int8
  bytes only, 1 byte/param/step, target ≈ 2× the bf16 path.

Each step maps x → x via ``tanh`` of (a tile of) the output, so the
chain is a real data dependence — a ``0·Σy`` pseudo-dependence gets
constant-folded and the matmul dead-code-eliminated (the first draft of
this tool "measured" 1.5 TB/s on an 819 GB/s chip that way).

Effective GB/s counts the WEIGHT bytes the formulation is supposed to
stream (bf16: 2·K·N; int8 paths: K·N) — above-HBM-peak output flags a
measurement artifact, kernel GB/s ≈ the dequant path flags DMA-
inefficient tiling (the v1 lesson: partial-row tiles DMA as short
strided segments).

Usage (claims the host TPU flock; refuses while a bench/watchdog
capture holds it): ``python tools/microbench_qdot.py [--m 8 32]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


SHAPES = [
    # (K, N, transpose_w, label)
    (4096, 4096, False, "attn_proj"),      # wq / wo
    (4096, 20480, False, "ffn_in"),        # w_in
    (20480, 4096, False, "ffn_out"),       # w_out
    (4096, 32000, True, "logits_embed"),   # (vocab, d) table: contract
                                           # d, emit vocab logits
]


def bench_shape(K: int, N: int, transpose_w: bool, label: str, M: int,
                budget_s: float = 90.0) -> dict:
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.bench_tpu import _chained_per_call
    from instaslice_tpu.models.quant import quantize_tensor
    from instaslice_tpu.ops.quant_matmul import quant_matmul

    kx, kw = jax.random.split(jax.random.key(0))
    x0 = jax.random.normal(kx, (M, K), jnp.bfloat16)
    wshape = (N, K) if transpose_w else (K, N)
    w32 = jax.random.normal(kw, wshape, jnp.float32) * K ** -0.5
    qt = quantize_tensor(w32.astype(jnp.bfloat16),
                         reduce_axis=-1 if transpose_w else -2)
    q, s = qt.q, qt.s
    w_bf16 = qt.dequantize(jnp.bfloat16)
    sub = "mk,nk->mn" if transpose_w else "mk,kn->mn"

    def dep(y):
        """(M, N) output → (M, K) next input, REAL data dependence on
        EVERY output column (tanh: bounded forever, not foldable; the
        row-sum term consumes all N columns — a bare y[:, :K] slice
        lets XLA dead-code-eliminate the other N-K output columns and
        stream 1/5 of the ffn_in weight, which first "measured"
        3.2 TB/s on an 819 GB/s chip)."""
        total = jnp.sum(y, axis=1, keepdims=True)    # consumes all N
        if N >= K:
            t = y[:, :K] + total
        else:
            t = jnp.concatenate(
                [y] * (K // N + 1), axis=1)[:, :K] + total
        return jnp.tanh(t).astype(jnp.bfloat16)

    def step_bf16(x):
        return dep(jnp.einsum(sub, x, w_bf16,
                              preferred_element_type=jnp.float32))

    def step_dequant(x):
        w = (q.astype(jnp.float32) * s.astype(jnp.float32)
             ).astype(jnp.bfloat16)
        return dep(jnp.einsum(sub, x, w,
                              preferred_element_type=jnp.float32))

    def step_kernel(x):
        return dep(quant_matmul(x, q, s, transpose_w=transpose_w))

    bytes_bf16 = 2 * K * N
    bytes_int8 = K * N
    out = {"label": label, "M": M, "K": K, "N": N}
    for name, fn, nbytes in (
        ("bf16", step_bf16, bytes_bf16),
        ("dequant", step_dequant, bytes_int8),
        ("kernel", step_kernel, bytes_int8),
    ):
        stats: dict = {}
        dt = _chained_per_call(fn, x0, n=8, stats=stats,
                               budget_s=budget_s)
        out[f"{name}_us"] = round(dt * 1e6, 1)
        out[f"{name}_eff_gbps"] = round(nbytes / dt / 1e9, 1)
        out[f"{name}_chain_n"] = stats.get("chain_n")
        out[f"{name}_spread_pct"] = stats.get("spread_pct")
    out["rtt_ms"] = stats.get("rtt_ms")
    if out["kernel_us"]:
        out["kernel_speedup_vs_bf16"] = round(
            out["bf16_us"] / out["kernel_us"], 2)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--budget-s", type=float, default=90.0)
    ap.add_argument("--shapes", default="",
                    help="comma-separated label filter")
    args = ap.parse_args(argv)

    from instaslice_tpu.utils.tpulock import TpuBusyError, TpuClaim

    try:
        claim = TpuClaim().acquire(timeout=10)
    except TpuBusyError as e:
        print(f"TPU busy (capture in progress?): {e}", file=sys.stderr)
        return 1
    try:
        import jax

        if jax.default_backend() != "tpu":
            print(f"not on TPU (backend={jax.default_backend()}); "
                  "refusing to microbench the CPU emulator",
                  file=sys.stderr)
            return 1
        labels = {l for l in args.shapes.split(",") if l}
        for M in args.m:
            for K, N, t, label in SHAPES:
                if labels and label not in labels:
                    continue
                r = bench_shape(K, N, t, label, M,
                                budget_s=args.budget_s)
                print(json.dumps(r), flush=True)
        return 0
    finally:
        claim.release()


if __name__ == "__main__":
    sys.exit(main())
