#!/usr/bin/env python
"""slicecheck — whole-program guarded-by + dispatch-hygiene analysis.

slicelint polices single-site contracts (one call, one literal); this
tool checks the two invariants that need a *program-wide* view, the
ones PR 15/16's chaos sweeps showed survive runtime lockcheck (which
only sees the schedules a 3-seed sweep happens to explore):

concurrency (guarded-by verification)
  Shared mutable fields are declared in the class body via
  ``guarded_by("lock-name")`` annotations (utils/guards.py; names come
  from the lockcheck factory registry). slicecheck discovers thread
  entry points (``threading.Thread(target=...)``, ``Thread``
  subclasses' ``run``, HTTP handler ``do_*`` methods), builds a
  per-class field-access map across ALL analyzed files, and reports:

  ==================  ==================================================
  rule id             invariant
  ==================  ==================================================
  guarded-field       every read/write of a ``guarded_by`` field sits
                      inside ``with <its named lock>:`` (same
                      receiver), or in a ``@requires``-marked helper,
                      or in ``__init__``/``__del__``
  undeclared-shared   a field of a concurrent class (one that owns a
                      named lock or a thread entry) written outside
                      ``__init__`` and reachable from >= 2 distinct
                      thread roots must carry a ``guarded_by`` or
                      ``unguarded("why")`` declaration
  guard-unknown-lock  a declaration names a lock with no
                      ``named_lock``/``named_rlock``/
                      ``named_condition`` factory site
  unbalanced-pair     a function that both opens and closes a paired
                      resource (pool allocate/fork->release, radix
                      lock->unlock, lock acquire->release) has a
                      return/raise path between them with the close
                      not in a ``finally``
  ==================  ==================================================

dispatch hygiene (hot-path modules: serving/engine*, serving/kvcache,
serving/sampling, models/)
  The "two programs" rule (PR 10) is only real if nothing in the
  decode/prefill path silently syncs the host or mints a new compiled
  shape:

  ==================  ==================================================
  host-sync-in-loop   ``.item()`` / ``.tolist()`` /
                      ``.block_until_ready()`` / ``jax.device_get`` /
                      ``np.asarray`` inside a loop, or
                      ``float``/``int``/``bool`` wrapping a jit-program
                      call — a per-iteration device round-trip
  nonstatic-shape-arg jit-wrapped function has a shape-bearing Python
                      parameter (n_steps, attend_len, k, ...) missing
                      from ``static_argnames``
  unbudgeted-jit      a ``jax.jit`` site in an engine module whose
                      program is not a ``self._X = jax.jit(...)``
                      assignment accounted in ``compile_budget()``
  ==================  ==================================================

catalog hygiene
  ==================  ==================================================
  dead-reason         a ``REASON_*`` constant in the reason catalog
                      (the module defining ``EVENT_REASONS``) with no
                      emit site anywhere in the analyzed program
  ==================  ==================================================

Suppression: append ``# slicecheck: disable=<rule>[,<rule>...]`` to
the reported line; whole-file ``# slicecheck: disable-file=<rule>``
within the first 25 lines — same grammar as slicelint, different tag
so the two gates can't mask each other. Suppressions are for
*justified* exceptions: pair them with a comment saying why.

Usage::

    python tools/slicecheck.py [--list-rules] [--dump-guards] [paths...]

Default paths: ``instaslice_tpu`` and ``tools`` next to this script.
The path set IS the program: rules that need whole-program knowledge
(entry points, emit sites, factory registry) see exactly these files,
which is what makes the fixture corpus under ``tests/check_fixtures/``
self-contained. Exit status 1 when findings remain, 0 on clean.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RULES: Dict[str, str] = {
    "guarded-field": (
        "guarded_by field accessed outside a `with <named lock>` block "
        "— take the lock, mark the helper @requires, or move the "
        "access under the existing critical section"
    ),
    "undeclared-shared": (
        "field of a concurrent class written outside __init__ and "
        "reachable from >= 2 thread roots with no guarded_by/unguarded "
        "declaration — declare which lock guards it, or unguarded(why)"
    ),
    "guard-unknown-lock": (
        "guarded_by names a lock with no named_lock/named_rlock/"
        "named_condition factory site — lock names come from the "
        "lockcheck registry"
    ),
    "unbalanced-pair": (
        "paired resource (allocate/release, lock/unlock, fork/release, "
        "acquire/release) can leak on a return/raise path — close in a "
        "finally, or restructure so the open escapes the function"
    ),
    "host-sync-in-loop": (
        "device->host sync inside a hot-path loop (.item/.tolist/"
        "device_get/block_until_ready/np.asarray, or float/int/bool of "
        "a jit program's result) — hoist to one batched readback per "
        "step"
    ),
    "nonstatic-shape-arg": (
        "jit-wrapped function takes a shape-bearing Python value "
        "(n_steps, *_len, k, ...) not listed in static_argnames — a "
        "traced shape value silently degrades or retraces"
    ),
    "unbudgeted-jit": (
        "jax.jit program in an engine module not assigned to a self._X "
        "attribute accounted in compile_budget() — every compiled "
        "program must belong to the declared bounded set"
    ),
    "dead-reason": (
        "reason constant in the catalog with no emit site in the "
        "program — delete it or wire the emitter it was meant for"
    ),
}

_SUPPRESS_RE = re.compile(r"#\s*slicecheck:\s*disable=([a-z\-,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*slicecheck:\s*disable-file=([a-z\-,\s]+)"
)

#: hot-path module markers for the dispatch-hygiene family
HOT_PATH_MARKERS = (
    "serving/engine",
    "serving/kvcache.py",
    "serving/sampling.py",
    "/models/",
    "models/",
)

#: engine modules where every jit program must be budget-accounted
ENGINE_MARKERS = ("serving/engine",)

_FACTORY_NAMES = {"named_lock", "named_rlock", "named_condition"}

#: attribute calls that mutate a container in place — a write for the
#: purposes of guarded-by analysis even though the AST ctx is Load
_MUTATOR_ATTRS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "extend", "insert", "setdefault",
    "sort", "reverse",
}

#: constructors whose values synchronize themselves — fields holding
#: one are exempt from undeclared-shared (Queue/Event/local do their
#: own locking; a Thread handle is set once before start)
_SELF_SYNC_CALLS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "threading.Event", "threading.local",
    "threading.Thread", "threading.Barrier", "threading.Semaphore",
    "threading.BoundedSemaphore",
}

#: paired-resource protocol: open method -> close method (matched on
#: the same receiver expression within one function)
_PAIRS = {
    "allocate": "release",
    "fork": "release",
    "lock": "unlock",
    "acquire": "release",
}

#: explicit host-sync attribute calls (any receiver)
_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
#: explicit host-sync dotted calls (post alias resolution)
_SYNC_CALLS = {"jax.device_get", "numpy.asarray", "numpy.array"}

#: parameter-name segments that mark a Python value as shape-bearing
_SHAPE_SEGMENTS = {
    "n", "num", "len", "length", "steps", "size", "count", "k",
    "width", "depth", "blocks", "pages", "cap", "budget",
}

SKIP_FILES = ("_pb2.py",)


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message}"
        )


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _recv_key(node: ast.AST) -> str:
    """Stable text for a receiver expression ('self', 'outer', 'p',
    'self.pool', ...) so `with p.lock:` can be matched to `p.done`."""
    try:
        return ast.unparse(node)
    except Exception:  # slicelint: disable=broad-except
        # pragma: no cover — any unparse failure degrades to the dump
        # form (still a stable key, just uglier); nothing to log from
        # a pure text-keying helper
        return ast.dump(node)


@dataclass
class _Decl:
    lock: Optional[str]  # None => unguarded(...)
    reason: Optional[str]
    node: ast.AST
    reads: str = "locked"  # "racy" => only writes are verified


@dataclass
class _Access:
    attr: str
    node: ast.AST
    write: bool
    recv: str           # receiver expression text
    is_self: bool
    held: List[Tuple[str, str]] = field(default_factory=list)
    # held: (lock attr name OR resolved lock name, receiver text)


@dataclass
class _MethodInfo:
    name: str
    node: ast.AST
    requires: Set[str] = field(default_factory=set)
    self_calls: Set[str] = field(default_factory=set)
    accesses: List[_Access] = field(default_factory=list)
    entry: bool = False
    roots: Set[str] = field(default_factory=set)


@dataclass
class _ClassInfo:
    name: str
    file: "_File"
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    decls: Dict[str, _Decl] = field(default_factory=dict)
    methods: Dict[str, _MethodInfo] = field(default_factory=dict)
    assigned: Set[str] = field(default_factory=set)
    self_sync: Set[str] = field(default_factory=set)

    @property
    def concurrent(self) -> bool:
        return bool(self.lock_attrs) or self.is_thread or any(
            m.entry for m in self.methods.values()
        )

    @property
    def is_thread(self) -> bool:
        return any(b.endswith("Thread") for b in self.bases)

    @property
    def is_handler(self) -> bool:
        return any("HTTPRequestHandler" in b for b in self.bases)


class _File:
    def __init__(self, path: str, display: str, source: str) -> None:
        self.path = path
        self.display = display
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.error: Optional[Finding] = None
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.aliases: Dict[str, str] = {}
        self.suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressed[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            if i <= 25:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self.file_suppressed |= {
                        r.strip() for r in m.group(1).split(",")
                        if r.strip()
                    }
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.error = Finding(
                display, e.lineno or 1, (e.offset or 0) + 1,
                "syntax-error", str(e.msg),
            )
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.module_names: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                        self.module_names.add(a.asname)
                    else:
                        self.module_names.add(a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )

    def resolve(self, dotted: str) -> str:
        if not dotted:
            return dotted
        first, _, rest = dotted.partition(".")
        origin = self.aliases.get(first)
        if origin is None:
            return dotted
        return f"{origin}.{rest}" if rest else origin

    def is_hot(self) -> bool:
        norm = self.display.replace(os.sep, "/")
        return any(m in norm for m in HOT_PATH_MARKERS)

    def is_engine(self) -> bool:
        norm = self.display.replace(os.sep, "/")
        return any(m in norm for m in ENGINE_MARKERS)


class Checker:
    """Whole-program analysis over one set of files."""

    def __init__(self) -> None:
        self.files: List[_File] = []
        self.classes: List[_ClassInfo] = []
        self.findings: List[Finding] = []
        self._emitted: Set[Tuple[str, int, str, str]] = set()
        #: every constant lock name passed to a factory, anywhere
        self.lock_registry: Set[str] = set()
        #: lock attr name -> set of lock names (for with-resolution)
        self.lock_attr_names: Dict[str, Set[str]] = {}
        #: field name -> classes assigning it via self (for cross-class
        #: attribution; only unique owners participate)
        self.field_owner: Dict[str, List[_ClassInfo]] = {}

    # -------------------------------------------------------- plumbing

    def add_file(self, path: str, display: str) -> None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        self.files.append(_File(path, display, source))

    def emit(self, fobj: _File, node: ast.AST, rule: str,
             message: str, tag: str = "") -> None:
        line = getattr(node, "lineno", 1)
        if rule in fobj.file_suppressed:
            return
        if rule in fobj.suppressed.get(line, ()):
            return
        key = (fobj.display, line, rule, tag or message)
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.findings.append(Finding(
            fobj.display, line, getattr(node, "col_offset", 0) + 1,
            rule, message,
        ))

    # ------------------------------------------------------------- run

    def run(self) -> List[Finding]:
        for fobj in self.files:
            if fobj.error is not None:
                self.findings.append(fobj.error)
        self._collect_classes()
        self._collect_entries()
        self._propagate_roots()
        self._check_guarded_fields()
        self._check_undeclared_shared()
        self._check_unknown_locks()
        for fobj in self.files:
            if fobj.tree is None:
                continue
            self._check_pairs(fobj)
            if fobj.is_hot():
                self._check_host_sync(fobj)
                self._check_jit_shapes(fobj)
            if fobj.is_engine():
                self._check_jit_budget(fobj)
        self._check_dead_reasons()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return self.findings

    # ------------------------------------------------- class collection

    def _collect_classes(self) -> None:
        for fobj in self.files:
            if fobj.tree is None:
                continue
            for node in ast.walk(fobj.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes.append(self._scan_class(fobj, node))
            # register factory lock names everywhere (module level too)
            for node in ast.walk(fobj.tree):
                if isinstance(node, ast.Call):
                    name = self._factory_name(fobj, node)
                    if name:
                        self.lock_registry.add(name)
        for cls in self.classes:
            for attr, lock in cls.lock_attrs.items():
                self.lock_attr_names.setdefault(attr, set()).add(lock)
            for f in cls.assigned | set(cls.decls):
                self.field_owner.setdefault(f, []).append(cls)

    def _factory_name(self, fobj: _File, call: ast.Call) -> Optional[str]:
        dotted = fobj.resolve(_dotted(call.func))
        if dotted.rsplit(".", 1)[-1] not in _FACTORY_NAMES:
            return None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    def _scan_class(self, fobj: _File, node: ast.ClassDef) -> _ClassInfo:
        cls = _ClassInfo(name=node.name, file=fobj, node=node)
        cls.bases = [fobj.resolve(_dotted(b)) for b in node.bases]
        # guarded_by / unguarded declarations in the class body
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ) and isinstance(stmt.annotation, ast.Call):
                fn = _dotted(stmt.annotation.func).rsplit(".", 1)[-1]
                arg = None
                if stmt.annotation.args and isinstance(
                    stmt.annotation.args[0], ast.Constant
                ):
                    arg = stmt.annotation.args[0].value
                if fn == "guarded_by" and isinstance(arg, str):
                    reads = "locked"
                    for kw in stmt.annotation.keywords:
                        if kw.arg == "reads" and isinstance(
                            kw.value, ast.Constant
                        ):
                            reads = str(kw.value.value)
                    cls.decls[stmt.target.id] = _Decl(
                        arg, None, stmt, reads,
                    )
                elif fn == "unguarded":
                    cls.decls[stmt.target.id] = _Decl(
                        None, arg if isinstance(arg, str) else "", stmt,
                    )
        # class-body fields (dataclass-style annotations, class attrs)
        # count as owned fields so cross-class attribution by name
        # lands on the right class — or goes ambiguous and is skipped
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                cls.assigned.add(stmt.target.id)
            elif isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        cls.assigned.add(tgt.id)
        # methods = FunctionDefs directly in the class body
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls.methods[stmt.name] = _MethodInfo(stmt.name, stmt)
        for m in cls.methods.values():
            self._scan_method(fobj, cls, m)
        return cls

    def _self_name(self, node: ast.AST) -> str:
        args = getattr(node, "args", None)
        if args and args.args:
            return args.args[0].arg
        return "self"

    def _scan_method(self, fobj: _File, cls: _ClassInfo,
                     m: _MethodInfo) -> None:
        selfname = self._self_name(m.node)
        for deco in m.node.decorator_list:
            if isinstance(deco, ast.Call) and _dotted(deco.func).rsplit(
                ".", 1
            )[-1] == "requires" and deco.args and isinstance(
                deco.args[0], ast.Constant
            ) and isinstance(deco.args[0].value, str):
                m.requires.add(deco.args[0].value)
        for node in ast.walk(m.node):
            # lock attribute creation: self.X = named_lock("...")
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                lock = self._factory_name(fobj, node.value)
                sync = fobj.resolve(_dotted(node.value.func))
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                        tgt.value, ast.Name
                    ) and tgt.value.id == selfname:
                        if lock:
                            cls.lock_attrs[tgt.attr] = lock
                        elif sync in _SELF_SYNC_CALLS or sync.rsplit(
                            ".", 1
                        )[-1] in _FACTORY_NAMES:
                            cls.self_sync.add(tgt.attr)
            if not isinstance(node, ast.Attribute):
                continue
            if not isinstance(node.value, ast.Name) or \
                    node.value.id != selfname:
                continue
            cls_method = node.attr in cls.methods
            parent = fobj.parents.get(node)
            if cls_method:
                # self.m(...) or self.m passed around: a call edge
                m.self_calls.add(node.attr)
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if isinstance(parent, ast.Attribute) and \
                    parent.attr in _MUTATOR_ATTRS:
                gp = fobj.parents.get(parent)
                if isinstance(gp, ast.Call) and gp.func is parent:
                    is_write = True
            if isinstance(parent, ast.Subscript) and isinstance(
                parent.ctx, (ast.Store, ast.Del)
            ) and parent.value is node:
                is_write = True
            if isinstance(node.ctx, ast.Store):
                cls.assigned.add(node.attr)
            m.accesses.append(_Access(
                node.attr, node, is_write, selfname, True,
                self._held_at(fobj, node),
            ))
        # non-self attribute accesses: collected globally later

    def _held_at(self, fobj: _File, node: ast.AST) -> List[Tuple[str, str]]:
        """(lock attr name or resolved lock name, receiver text) for
        every with-lock lexically enclosing ``node`` within its own
        function scope (a with outside a nested def does not guarantee
        anything about when the closure runs)."""
        held: List[Tuple[str, str]] = []
        cur = fobj.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Attribute):
                        held.append(
                            (expr.attr, _recv_key(expr.value))
                        )
                    elif isinstance(expr, ast.Name):
                        held.append((expr.id, "<module>"))
            cur = fobj.parents.get(cur)
        return held

    # --------------------------------------------------- entry points

    def _class_of(self, fobj: _File, node: ast.AST) -> Optional[_ClassInfo]:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                for cls in self.classes:
                    if cls.node is cur and cls.file is fobj:
                        return cls
            cur = fobj.parents.get(cur)
        return None

    def _method_of(self, fobj: _File,
                   node: ast.AST) -> Optional[Tuple[_ClassInfo, str]]:
        cur: Optional[ast.AST] = node
        fn: Optional[str] = None
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = cur.name
                parent = fobj.parents.get(cur)
                if isinstance(parent, ast.ClassDef):
                    cls = self._class_of(fobj, parent)
                    if cls and fn in cls.methods:
                        return cls, fn
            cur = fobj.parents.get(cur)
        return None

    def _collect_entries(self) -> None:
        by_name: Dict[str, List[Tuple[_ClassInfo, str]]] = {}
        for cls in self.classes:
            for mname in cls.methods:
                by_name.setdefault(mname, []).append((cls, mname))
            if cls.is_thread and "run" in cls.methods:
                cls.methods["run"].entry = True
            if cls.is_handler:
                for mname, m in cls.methods.items():
                    if mname.startswith("do_"):
                        m.entry = True
        for fobj in self.files:
            if fobj.tree is None:
                continue
            for node in ast.walk(fobj.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = fobj.resolve(_dotted(node.func))
                target: Optional[ast.AST] = None
                if dotted == "threading.Thread" or \
                        dotted.endswith(".Thread"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif dotted.rsplit(".", 1)[-1].endswith("Manager"):
                    # reconcile Manager worker bodies: the callback
                    # runs on the worker pool's threads
                    for kw in node.keywords:
                        if kw.arg == "reconcile":
                            target = kw.value
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "submit" and node.args:
                    target = node.args[0]
                if not isinstance(target, ast.Attribute):
                    continue
                mname = target.attr
                owner = self._class_of(fobj, node)
                if owner is not None and isinstance(
                    target.value, ast.Name
                ) and mname in owner.methods:
                    owner.methods[mname].entry = True
                    continue
                candidates = by_name.get(mname, [])
                if len(candidates) == 1:
                    candidates[0][0].methods[mname].entry = True

    def _propagate_roots(self) -> None:
        for cls in self.classes:
            for mname, m in cls.methods.items():
                if m.entry:
                    m.roots.add(f"{cls.name}.{mname}")
                elif not mname.startswith("_"):
                    # public API: callable from any other thread
                    m.roots.add("external")
            changed = True
            while changed:
                changed = False
                for m in cls.methods.values():
                    for callee in m.self_calls:
                        tgt = cls.methods.get(callee)
                        if tgt is None:
                            continue
                        before = len(tgt.roots)
                        tgt.roots |= m.roots
                        if len(tgt.roots) != before:
                            changed = True
            for mname, m in cls.methods.items():
                if not m.roots and mname not in ("__init__", "__del__"):
                    m.roots.add("external")

    # -------------------------------------------- cross-class accesses

    def _iter_foreign_accesses(self):
        """Attribute accesses whose receiver is not the local ``self``
        but whose attr name is owned by exactly one analyzed class:
        yields (file, owner_cls, access, context_method_or_None)."""
        for fobj in self.files:
            if fobj.tree is None:
                continue
            for node in ast.walk(fobj.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                owners = self.field_owner.get(node.attr, [])
                if len(owners) != 1:
                    continue
                owner = owners[0]
                ctx = self._method_of(fobj, node)
                if ctx is not None and ctx[0] is owner and isinstance(
                    node.value, ast.Name
                ) and node.value.id == self._self_name(
                    ctx[0].methods[ctx[1]].node
                ):
                    continue  # the owning class's own self access
                # skip module receivers (json.loads, np.float32, ...)
                recv_root = _dotted(node.value).split(".")[0]
                if recv_root and (
                    recv_root in fobj.module_names
                    or recv_root in fobj.aliases
                ):
                    continue
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                parent = fobj.parents.get(node)
                if isinstance(parent, ast.Attribute) and \
                        parent.attr in _MUTATOR_ATTRS:
                    gp = fobj.parents.get(parent)
                    if isinstance(gp, ast.Call) and gp.func is parent:
                        is_write = True
                if isinstance(parent, ast.Subscript) and isinstance(
                    parent.ctx, (ast.Store, ast.Del)
                ) and parent.value is node:
                    is_write = True
                acc = _Access(
                    node.attr, node, is_write, _recv_key(node.value),
                    False, self._held_at(fobj, node),
                )
                yield fobj, owner, acc, ctx

    # ------------------------------------------------- guarded fields

    def _satisfied(self, owner: _ClassInfo, lock: str,
                   acc: _Access, ctx) -> bool:
        if ctx is not None:
            cls, mname = ctx
            m = cls.methods[mname]
            if lock in m.requires:
                return True
            if cls is owner and mname in ("__init__", "__del__"):
                return True
        for held_name, held_recv in acc.held:
            # exact lock-name match (resolved through any class's
            # uniquely-named lock attr, or a module-level lock)
            names = self.lock_attr_names.get(held_name, set())
            if held_name == lock:
                return True
            if names == {lock}:
                return True
            # receiver-typed match: with <recv>.<attr> where <attr> is
            # the owner class's lock attr for this lock and <recv> is
            # the same expression the field is accessed through
            if owner.lock_attrs.get(held_name) == lock and \
                    held_recv == acc.recv:
                return True
        return False

    def _check_guarded_fields(self) -> None:
        for cls in self.classes:
            for mname, m in cls.methods.items():
                for acc in m.accesses:
                    decl = cls.decls.get(acc.attr)
                    if decl is None or decl.lock is None:
                        continue
                    if decl.reads == "racy" and not acc.write:
                        continue
                    if self._satisfied(cls, decl.lock, acc,
                                       (cls, mname)):
                        continue
                    self.emit(
                        cls.file, acc.node, "guarded-field",
                        f"{cls.name}.{acc.attr} "
                        f"({'write' if acc.write else 'read'}) outside "
                        f"`with <{decl.lock}>` — declared "
                        f"guarded_by({decl.lock!r})",
                        tag=acc.attr,
                    )
        for fobj, owner, acc, ctx in self._iter_foreign_accesses():
            decl = owner.decls.get(acc.attr)
            if decl is None or decl.lock is None:
                continue
            if decl.reads == "racy" and not acc.write:
                continue
            if self._satisfied(owner, decl.lock, acc, ctx):
                continue
            self.emit(
                fobj, acc.node, "guarded-field",
                f"{owner.name}.{acc.attr} "
                f"({'write' if acc.write else 'read'}) via "
                f"`{acc.recv}` outside `with <{decl.lock}>` — declared "
                f"guarded_by({decl.lock!r})",
                tag=acc.attr,
            )

    # --------------------------------------------- undeclared sharing

    def _check_undeclared_shared(self) -> None:
        # roots contributed by foreign accessors, keyed by class+field
        foreign_roots: Dict[Tuple[int, str], Set[str]] = {}
        foreign_writes: Dict[Tuple[int, str], bool] = {}
        for fobj, owner, acc, ctx in self._iter_foreign_accesses():
            key = (id(owner), acc.attr)
            roots = foreign_roots.setdefault(key, set())
            if ctx is not None:
                roots |= ctx[0].methods[ctx[1]].roots
            else:
                roots.add("external")
            if acc.write:
                foreign_writes[key] = True
        for cls in self.classes:
            if not cls.concurrent:
                continue
            fields: Dict[str, Set[str]] = {}
            writes: Set[str] = set()
            for mname, m in cls.methods.items():
                for acc in m.accesses:
                    if acc.attr in cls.lock_attrs or \
                            acc.attr in cls.self_sync:
                        continue
                    if mname == "__init__":
                        continue
                    fields.setdefault(acc.attr, set()).update(m.roots)
                    if acc.write:
                        writes.add(acc.attr)
            for attr, roots in fields.items():
                if attr in cls.decls:
                    continue
                key = (id(cls), attr)
                roots = roots | foreign_roots.get(key, set())
                written = attr in writes or foreign_writes.get(
                    key, False
                )
                if written and len(roots) >= 2:
                    node = cls.node
                    # report at the first access inside the class
                    for m in cls.methods.values():
                        for acc in m.accesses:
                            if acc.attr == attr:
                                node = acc.node
                                break
                        else:
                            continue
                        break
                    self.emit(
                        cls.file, node, "undeclared-shared",
                        f"{cls.name}.{attr} written outside __init__ "
                        f"and reachable from {len(roots)} thread roots "
                        f"({', '.join(sorted(roots))}) with no "
                        "guarded_by/unguarded declaration",
                        tag=attr,
                    )

    def _check_unknown_locks(self) -> None:
        for cls in self.classes:
            for fname, decl in cls.decls.items():
                if decl.lock is not None and \
                        decl.lock not in self.lock_registry:
                    self.emit(
                        cls.file, decl.node, "guard-unknown-lock",
                        f"{cls.name}.{fname} guarded_by({decl.lock!r}) "
                        "— no factory site registers that name",
                        tag=fname,
                    )

    # --------------------------------------------------- paired opens

    def _check_pairs(self, fobj: _File) -> None:
        for node in ast.walk(fobj.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_pairs_in(fobj, node)

    def _in_finally(self, fobj: _File, node: ast.AST,
                    stop: ast.AST) -> bool:
        cur = fobj.parents.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, ast.Try):
                for fin in cur.finalbody:
                    for sub in ast.walk(fin):
                        if sub is node:
                            return True
            cur = fobj.parents.get(cur)
        return False

    @classmethod
    def _walk_scope(cls, node: ast.AST, root: bool = True):
        """ast.walk that stays in one function scope: nested defs and
        lambdas open their own open/close discipline."""
        if not root and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from cls._walk_scope(child, root=False)

    def _check_pairs_in(self, fobj: _File, fn: ast.AST) -> None:
        opens: Dict[Tuple[str, str], List[ast.Call]] = {}
        closes: Dict[Tuple[str, str], List[ast.Call]] = {}
        exits: List[ast.AST] = []
        for node in self._walk_scope(fn):
            if isinstance(node, (ast.Return, ast.Raise)):
                exits.append(node)
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            recv = _recv_key(node.func.value)
            if attr in _PAIRS:
                # `with lock.acquire()`-style or `with x:` handled by
                # guarded-by; skip acquire calls used as context exprs
                parent = fobj.parents.get(node)
                if isinstance(parent, ast.withitem):
                    continue
                opens.setdefault((recv, _PAIRS[attr]), []).append(node)
            if attr in set(_PAIRS.values()):
                closes.setdefault((recv, attr), []).append(node)
        for (recv, closer), open_calls in opens.items():
            close_calls = closes.get((recv, closer), [])
            if not close_calls:
                continue  # ownership transfer out of the function
            first_open = min(c.lineno for c in open_calls)
            unsafe_close = [
                c for c in close_calls
                if not self._in_finally(fobj, c, fn)
            ]
            if not unsafe_close:
                continue
            last_close = max(c.lineno for c in unsafe_close)
            leaky = [
                e for e in exits
                if first_open < e.lineno < last_close
                and not self._guards_failed_open(fobj, e, open_calls)
            ]
            if leaky:
                self.emit(
                    fobj, open_calls[0], "unbalanced-pair",
                    f"`{recv}` opened here but the matching "
                    f".{closer}() at line {last_close} is skipped by "
                    f"the return/raise at line {leaky[0].lineno} — "
                    "close in a finally",
                    tag=f"{recv}.{closer}",
                )

    def _guards_failed_open(self, fobj: _File, exit_node: ast.AST,
                            open_calls: List[ast.Call]) -> bool:
        """An exit inside the except handler of the try that contains
        the open itself runs only when the open FAILED — nothing was
        acquired, so it cannot leak."""
        cur = fobj.parents.get(exit_node)
        while cur is not None:
            if isinstance(cur, ast.ExceptHandler):
                try_node = fobj.parents.get(cur)
                if isinstance(try_node, ast.Try):
                    for stmt in try_node.body:
                        for sub in ast.walk(stmt):
                            if sub in open_calls:
                                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = fobj.parents.get(cur)
        return False

    # ----------------------------------------------- dispatch hygiene

    def _in_loop(self, fobj: _File, node: ast.AST) -> bool:
        cur = fobj.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.While, ast.For, ast.AsyncFor)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
            cur = fobj.parents.get(cur)
        return False

    def _jit_attr_names(self, fobj: _File) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fobj.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ) and fobj.resolve(_dotted(node.value.func)) == "jax.jit":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        names.add(tgt.attr)
        return names

    def _check_host_sync(self, fobj: _File) -> None:
        jit_attrs = self._jit_attr_names(fobj)
        for node in ast.walk(fobj.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._in_loop(fobj, node):
                continue
            dotted = fobj.resolve(_dotted(node.func))
            if dotted in _SYNC_CALLS:
                self.emit(
                    fobj, node, "host-sync-in-loop",
                    f"{dotted.rsplit('.', 1)[-1]}() inside a loop — "
                    "one device->host sync per iteration; hoist to a "
                    "single batched readback",
                )
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTRS and not node.args:
                self.emit(
                    fobj, node, "host-sync-in-loop",
                    f".{node.func.attr}() inside a loop — one "
                    "device->host sync per iteration; hoist to a "
                    "single batched readback",
                )
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and isinstance(
                        node.args[0], ast.Call):
                inner = node.args[0]
                inner_dotted = fobj.resolve(_dotted(inner.func))
                is_jit = (
                    isinstance(inner.func, ast.Attribute)
                    and inner.func.attr in jit_attrs
                )
                if is_jit or inner_dotted.startswith("jax.numpy.") or \
                        inner_dotted.startswith("jax."):
                    self.emit(
                        fobj, node, "host-sync-in-loop",
                        f"{node.func.id}(<device value>) inside a loop "
                        "forces a blocking transfer per iteration",
                    )

    def _static_names(self, call: ast.Call) -> Optional[Set[str]]:
        for kw in call.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                out: Set[str] = set()
                val = kw.value
                elts = val.elts if isinstance(
                    val, (ast.Tuple, ast.List, ast.Set)
                ) else [val]
                for e in elts:
                    if isinstance(e, ast.Constant):
                        out.add(str(e.value))
                return out
        return None

    def _shapeish(self, name: str) -> bool:
        return any(
            seg in _SHAPE_SEGMENTS for seg in name.lower().split("_")
        )

    def _check_jit_shapes(self, fobj: _File) -> None:
        # map function name -> def node (methods + module functions)
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(fobj.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        for node in ast.walk(fobj.tree):
            if not isinstance(node, ast.Call) or \
                    fobj.resolve(_dotted(node.func)) != "jax.jit":
                continue
            if not node.args:
                continue
            wrapped = node.args[0]
            fn_name = None
            if isinstance(wrapped, ast.Attribute):
                fn_name = wrapped.attr
            elif isinstance(wrapped, ast.Name):
                fn_name = wrapped.id
            target = defs.get(fn_name or "")
            if target is None:
                continue
            statics = self._static_names(node) or set()
            params = [a.arg for a in target.args.args][1:] \
                if target.args.args and \
                target.args.args[0].arg in ("self", "cls") \
                else [a.arg for a in target.args.args]
            params += [a.arg for a in target.args.kwonlyargs]
            for p in params:
                if self._shapeish(p) and p not in statics:
                    self.emit(
                        fobj, node, "nonstatic-shape-arg",
                        f"jit of {fn_name}(): shape-bearing parameter "
                        f"{p!r} not in static_argnames — it will be "
                        "traced (silent degrade) instead of compiled "
                        "per bounded value",
                        tag=p,
                    )

    def _check_jit_budget(self, fobj: _File) -> None:
        budget: Set[str] = set()
        budget_fns = [
            n for n in ast.walk(fobj.tree)
            if isinstance(n, ast.FunctionDef)
            and n.name == "compile_budget"
        ]
        for fn in budget_fns:
            for node in ast.walk(fn):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    budget.add(node.value)
        if not budget_fns:
            return
        for node in ast.walk(fobj.tree):
            if not isinstance(node, ast.Call) or \
                    fobj.resolve(_dotted(node.func)) != "jax.jit":
                continue
            parent = fobj.parents.get(node)
            key = None
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    if isinstance(tgt, ast.Attribute):
                        key = tgt.attr.lstrip("_")
            if key is None:
                self.emit(
                    fobj, node, "unbudgeted-jit",
                    "jax.jit program not bound to a self._X attribute "
                    "— it cannot be accounted by compile_budget()",
                )
            elif key not in budget:
                self.emit(
                    fobj, node, "unbudgeted-jit",
                    f"jit program {key!r} missing from "
                    "compile_budget() — every compiled program belongs "
                    "to the declared bounded set",
                    tag=key,
                )

    # ------------------------------------------------- reason catalog

    def _check_dead_reasons(self) -> None:
        catalog: Optional[_File] = None
        catalog_tree = None
        for fobj in self.files:
            if fobj.tree is None:
                continue
            for node in ast.walk(fobj.tree):
                if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "EVENT_REASONS"
                    for t in node.targets
                ):
                    catalog, catalog_tree = fobj, fobj.tree
                    break
            if catalog:
                break
        if catalog is None:
            return
        reasons: Dict[str, ast.AST] = {}
        containers: Dict[str, Set[str]] = {}
        body = getattr(catalog_tree, "body", [])
        for stmt in body:
            if not isinstance(stmt, ast.Assign):
                continue
            tgt = stmt.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if re.match(r"^REASON_[A-Z0-9_]+$", tgt.id) and isinstance(
                stmt.value, ast.Constant
            ):
                reasons[tgt.id] = stmt
            elif tgt.id != "EVENT_REASONS":
                refs = {
                    n.id for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Name)
                    and n.id.startswith("REASON_")
                }
                if refs:
                    containers[tgt.id] = refs
        if not reasons:
            return
        used: Set[str] = set()
        container_used: Set[str] = set()
        for fobj in self.files:
            if fobj is catalog or fobj.tree is None:
                continue
            for node in ast.walk(fobj.tree):
                name = None
                if isinstance(node, ast.Name):
                    name = node.id
                elif isinstance(node, ast.Attribute):
                    name = node.attr
                if name is None:
                    continue
                if name.startswith("REASON_"):
                    used.add(name)
                elif name in containers:
                    container_used.add(name)
        for cname in container_used:
            used |= containers[cname]
        for rname, node in reasons.items():
            if rname not in used:
                self.emit(
                    catalog, node, "dead-reason",
                    f"{rname} has no emit site in the program — delete "
                    "it or wire the emitter it documents",
                    tag=rname,
                )

    # ----------------------------------------------------- guard dump

    def guard_map(self) -> Dict[str, Dict[str, Dict[str, Optional[str]]]]:
        out: Dict[str, Dict[str, Dict[str, Optional[str]]]] = {}
        for cls in self.classes:
            if not cls.decls:
                continue
            key = f"{cls.file.display}:{cls.name}"
            out[key] = {
                fname: {"lock": d.lock, "reason": d.reason,
                        "reads": d.reads}
                for fname, d in cls.decls.items()
            }
        return out


# ----------------------------------------------------------------- API


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def check_paths(paths: Iterable[str]) -> List[Finding]:
    return build_checker(paths).findings


def build_checker(paths: Iterable[str]) -> Checker:
    checker = Checker()
    for path in iter_python_files(paths):
        if any(path.endswith(skip) for skip in SKIP_FILES):
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        display = rel if not rel.startswith("..") else path
        checker.add_file(path, display)
    checker.run()
    return checker


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="slicecheck", description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*", help="files or directories "
                    "(default: instaslice_tpu + tools)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--dump-guards", action="store_true",
                    help="print the class -> field -> lock guard map "
                    "as JSON and exit 0")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}: {desc}")
        return 0
    paths = args.paths or [
        os.path.join(_REPO_ROOT, "instaslice_tpu"),
        os.path.join(_REPO_ROOT, "tools"),
    ]
    checker = build_checker(paths)
    if args.dump_guards:
        print(json.dumps(checker.guard_map(), indent=2, sort_keys=True))
        return 0
    for f in checker.findings:
        print(f)
    if checker.findings:
        print(
            f"slicecheck: {len(checker.findings)} finding(s) — fix, or "
            "suppress a justified site with "
            "'# slicecheck: disable=<rule>'",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
