#!/usr/bin/env python
"""slicelint — project-invariant static analysis for instaslice_tpu.

Generic linters check style; this one checks the *contracts the operator's
survival depends on* (PAPER.md: gate → allocate → realize → ungate must
never wedge), which no off-the-shelf tool knows about:

==================  =====================================================
rule id             invariant
==================  =====================================================
raw-http            HTTP round-trips go through a sanctioned transport
                    (``kube/real.py``'s retry+breaker wrapper for the
                    kube API; the allowlisted clients elsewhere). A raw
                    ``urllib.request.urlopen`` in a reconciler bypasses
                    retries, the circuit breaker, and tracing.
name-literal        Gate / finalizer / resource / annotation names are
                    spelled ONLY in ``instaslice_tpu/api/constants.py``.
                    A name inlined twice drifts twice (the reference
                    shipped — and could never fix — a misspelled gate).
broad-except        ``except Exception`` / bare ``except`` must log,
                    print, or re-raise. A handler that silently swallows
                    turns an injected fault into a wedged reconcile.
sleep-in-loop       No ``time.sleep()`` lexically inside a loop: loops
                    must pace on a stop event's ``.wait(timeout)`` so
                    drain/SIGTERM interrupts the nap (a sleeping
                    reconcile loop stretches every shutdown by its
                    period).
span-leak           ``tracer.span(...)`` is only sound as a ``with``
                    context manager — any other use can leave the span
                    (and its ambient-trace contextvar) open forever.
mutable-default     No mutable default arguments (shared-state bugs).
raw-lock            Locks are created via the named factory in
                    ``instaslice_tpu/utils/lockcheck.py`` so the runtime
                    lock-order detector sees every acquisition. A raw
                    ``threading.Lock()`` is invisible to it.
event-reason-literal  Flight-recorder ``reason=`` arguments (journal
                    ``emit``, ``emit_pod_event``) are constants from
                    ``instaslice_tpu/api/constants.py`` — a reason
                    inlined at the call site drifts out of the catalog,
                    the dashboards, and ``make events-check``.
==================  =====================================================

Suppression: append ``# slicelint: disable=<rule>[,<rule>...]`` to the
offending line (the line the finding is reported on). Whole-file:
``# slicelint: disable-file=<rule>[,...]`` anywhere in the first 25
lines. Suppressions are for *justified* exceptions — pair them with a
comment saying why.

Usage::

    python tools/slicelint.py [--list-rules] [paths...]

Default paths: ``instaslice_tpu`` and ``tools`` next to this script.
Exit status 1 when findings remain, 0 on a clean tree.
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

# The canonical names come from the one module allowed to spell them —
# reading them here keeps slicelint itself literal-free and means a
# renamed constant re-trains the linter automatically. Loaded straight
# from the file (constants.py is import-time pure by design) rather
# than through the package: the Dockerfiles run this gate BEFORE `pip
# install`, and going through instaslice_tpu/__init__ would couple the
# lint step to the whole api/topology import chain staying stdlib-only.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_constants():
    import importlib.util

    path = os.path.join(_REPO_ROOT, "instaslice_tpu", "api", "constants.py")
    spec = importlib.util.spec_from_file_location(
        "_slicelint_constants", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_names = _load_constants()

RULES: Dict[str, str] = {
    "raw-http": (
        "raw urllib/http.client call outside a sanctioned transport "
        "module — kube traffic must ride kube/real.py's retry+breaker "
        "wrapper"
    ),
    "name-literal": (
        "gate/finalizer/resource/annotation name spelled inline — use "
        "instaslice_tpu/api/constants.py"
    ),
    "broad-except": (
        "broad except swallows without logging or re-raising — narrow "
        "the type, or log with context / re-raise"
    ),
    "sleep-in-loop": (
        "time.sleep() inside a loop — pace on a stop event's "
        ".wait(timeout) so shutdown interrupts the nap"
    ),
    "span-leak": (
        "tracer span opened outside a with-statement — no guaranteed "
        "closing path"
    ),
    "mutable-default": "mutable default argument",
    "raw-lock": (
        "raw threading.Lock/RLock/Condition — create locks via "
        "instaslice_tpu.utils.lockcheck's named factory so the "
        "lock-order detector sees them"
    ),
    "event-reason-literal": (
        "event reason passed as a string literal — every journal/"
        "Kubernetes event reason must be a constant from "
        "instaslice_tpu/api/constants.py (the flight-recorder catalog)"
    ),
}

#: substrings that mark a string literal as a protected name
NAME_FRAGMENTS = (
    _names.GROUP,             # tpu.instaslice.dev
    _names.TPU_RESOURCE,      # google.com/tpu
    _names.LEGACY_GATE_NAME.split("/")[0],  # org.instaslice
)

#: modules allowed to urlopen: the kube transport itself, the HTTP test
#: server, and the serving/cloud clients that own their OWN retry layer
RAW_HTTP_ALLOW = (
    "instaslice_tpu/kube/real.py",
    "instaslice_tpu/kube/httptest.py",
    "instaslice_tpu/serving/loadgen.py",
    # the fleet router IS a transport: per-replica breaker + bounded
    # retry live in serving/router.py itself
    "instaslice_tpu/serving/router.py",
    "instaslice_tpu/device/cloudtpu.py",
    "instaslice_tpu/device/cloudtpu_mock.py",
    "instaslice_tpu/cli/tpuslicectl.py",
    # the fleet-telemetry aggregator IS a scrape transport: per-target
    # timeout + error accounting live in obs/telemetry.py itself, and
    # a scrape failure is counted, never retried (next poll re-reads)
    "instaslice_tpu/obs/telemetry.py",
    "tools/serve_capacity.py",
    "tools/telemetry_smoke.py",
    "tools/profile_smoke.py",
)

RAW_LOCK_ALLOW = ("instaslice_tpu/utils/lockcheck.py",)
NAME_LITERAL_ALLOW = ("instaslice_tpu/api/constants.py",)

#: generated code is not ours to lint
SKIP_FILES = ("_pb2.py",)

_RAW_HTTP_CALLS = {
    "urllib.request.urlopen",
    "urllib.request.Request",
    "urllib.request.build_opener",
    "http.client.HTTPConnection",
    "http.client.HTTPSConnection",
}
_RAW_LOCK_CALLS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
}
_LOG_METHOD_ATTRS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
}
_REPORT_FUNC_NAMES = {"print", "log"}

_SUPPRESS_RE = re.compile(r"#\s*slicelint:\s*disable=([a-z\-,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*slicelint:\s*disable-file=([a-z\-,\s]+)")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message}"
        )


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class _Linter:
    def __init__(self, path: str, display_path: str, source: str) -> None:
        self.path = path
        self.display = display_path
        self.source = source
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self.suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressed[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            if i <= 25:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self.file_suppressed |= {
                        r.strip() for r in m.group(1).split(",")
                        if r.strip()
                    }
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.aliases: Dict[str, str] = {}

    # ------------------------------------------------------------- core

    def _allowed(self, allowlist: Iterable[str]) -> bool:
        norm = self.display.replace(os.sep, "/")
        return any(norm.endswith(a) for a in allowlist)

    def emit(self, node: ast.AST, rule: str, message: str = "") -> None:
        line = getattr(node, "lineno", 1)
        if rule in self.file_suppressed:
            return
        if rule in self.suppressed.get(line, ()):
            return
        self.findings.append(Finding(
            self.display, line, getattr(node, "col_offset", 0) + 1,
            rule, message or RULES[rule],
        ))

    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.findings.append(Finding(
                self.display, e.lineno or 1, (e.offset or 0) + 1,
                "syntax-error", str(e.msg),
            ))
            return self.findings
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # type annotations reference threading.Lock legitimately (and
        # under PEP 563 they are never evaluated at all) — collect
        # their subtrees so the bare-reference rule can skip them
        self._ann_nodes: set = set()
        for node in ast.walk(tree):
            anns = []
            if isinstance(node, ast.AnnAssign):
                anns.append(node.annotation)
            elif isinstance(node, ast.arg) and node.annotation:
                anns.append(node.annotation)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.returns:
                anns.append(node.returns)
            for a in anns:
                for sub in ast.walk(a):
                    self._ann_nodes.add(id(sub))
        # alias map so `from threading import Lock` / `import
        # urllib.request as ur` cannot smuggle a policed call past the
        # dotted-name match: local binding -> canonical dotted origin
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                self._check_bare_lock_ref(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_except(node)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                self._check_name_literal(node)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                self._check_defaults(node)
        return self.findings

    # ------------------------------------------------------------ rules

    def _resolve(self, dotted: str) -> str:
        """Expand the leading segment through the import-alias map."""
        if not dotted:
            return dotted
        first, _, rest = dotted.partition(".")
        origin = self.aliases.get(first)
        if origin is None:
            return dotted
        return f"{origin}.{rest}" if rest else origin

    def _check_call(self, node: ast.Call) -> None:
        dotted = self._resolve(_dotted(node.func))
        if dotted in _RAW_HTTP_CALLS and not self._allowed(RAW_HTTP_ALLOW):
            self.emit(node, "raw-http")
        if dotted in _RAW_LOCK_CALLS and not self._allowed(RAW_LOCK_ALLOW):
            self.emit(node, "raw-lock")
        if dotted == "time.sleep" and self._in_loop(node):
            self.emit(node, "sleep-in-loop")
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "span"
            and self._is_tracer_expr(node.func.value)
            and not isinstance(self.parents.get(node), ast.withitem)
        ):
            self.emit(node, "span-leak")
        self._check_event_reason(node, dotted)

    def _check_bare_lock_ref(self, node) -> None:
        """An UNCALLED reference to ``threading.Lock``/``RLock``/
        ``Condition`` — ``defaultdict(threading.Lock)``, a
        ``factory=Lock`` default, ``locks = [Lock() for ...]``'s
        comprehension cousin ``map(Lock, range(n))`` — manufactures raw
        locks at a distance, past the call-site rule. Type annotations
        are exempt (naming the type is not making a lock)."""
        parent = self.parents.get(node)
        if isinstance(parent, ast.Call) and parent.func is node:
            return  # the direct-call form: _check_call owns it
        if isinstance(parent, ast.Attribute):
            return  # interior of a longer dotted chain
        if id(node) in self._ann_nodes:
            return
        dotted = self._resolve(_dotted(node))
        if dotted in _RAW_LOCK_CALLS and not self._allowed(RAW_LOCK_ALLOW):
            self.emit(node, "raw-lock")

    def _check_event_reason(self, node: ast.Call, dotted: str) -> None:
        """Journal emission (``<journal>.emit(...)`` /
        ``emit_pod_event(...)``, both with keyword-only ``reason=``)
        must name its reason via a constant, never a string literal —
        the reason catalog lives ONLY in api/constants.py."""
        is_emit = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
            and self._is_journal_expr(node.func.value)
        ) or dotted.rsplit(".", 1)[-1] == "emit_pod_event"
        if not is_emit:
            return
        for kw in node.keywords:
            if kw.arg == "reason" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                self.emit(
                    node, "event-reason-literal",
                    f"reason literal {kw.value.value!r} — use a "
                    "constant from instaslice_tpu/api/constants.py",
                )

    def _is_journal_expr(self, node: ast.AST) -> bool:
        """Does this receiver look like a journal? Scopes the rule to
        ``journal.emit`` / ``self.journal.emit`` / ``get_journal().emit``
        so unrelated ``emit()`` methods don't trip the gate."""
        if isinstance(node, ast.Call):
            return self._is_journal_expr(node.func)
        dotted = self._resolve(_dotted(node))
        if not dotted:
            return False
        return "journal" in dotted.rsplit(".", 1)[-1].lower()

    def _is_tracer_expr(self, node: ast.AST) -> bool:
        """Does this receiver look like a tracer? Scopes span-leak to
        ``tracer.span`` / ``self.tracer.span`` / ``get_tracer().span``
        so unrelated ``span()`` methods (e.g. ``re.Match.span``) don't
        trip the zero-tolerance gate."""
        if isinstance(node, ast.Call):
            return self._is_tracer_expr(node.func)
        dotted = self._resolve(_dotted(node))
        if not dotted:
            return False
        return "tracer" in dotted.rsplit(".", 1)[-1].lower()

    def _in_loop(self, node: ast.AST) -> bool:
        """Lexically inside a while/for of the SAME function scope."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.While, ast.For, ast.AsyncFor)):
                return True
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return False
            cur = self.parents.get(cur)
        return False

    def _check_except(self, node: ast.ExceptHandler) -> None:
        if not self._is_broad(node.type):
            return
        for sub in node.body:
            for n in self._walk_handler(sub):
                if isinstance(n, ast.Raise):
                    return
                if isinstance(n, ast.Call) and self._is_reporting(n):
                    return
        self.emit(node, "broad-except")

    @classmethod
    def _walk_handler(cls, node: ast.AST) -> Iterable[ast.AST]:
        """ast.walk that does NOT descend into nested function/lambda
        bodies: a raise or log call defined there runs later (if ever),
        so it cannot satisfy the handler's report-or-reraise duty."""
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        for child in ast.iter_child_nodes(node):
            yield from cls._walk_handler(child)

    @staticmethod
    def _is_broad(t: Optional[ast.AST]) -> bool:
        if t is None:
            return True  # bare except
        if isinstance(t, ast.Name):
            return t.id in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(
                isinstance(e, ast.Name)
                and e.id in ("Exception", "BaseException")
                for e in t.elts
            )
        return False

    @staticmethod
    def _is_reporting(call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _LOG_METHOD_ATTRS:
            return True
        if isinstance(f, ast.Name) and f.id in _REPORT_FUNC_NAMES:
            return True
        return False

    def _check_name_literal(self, node: ast.Constant) -> None:
        if self._allowed(NAME_LITERAL_ALLOW):
            return
        if not any(frag in node.value for frag in NAME_FRAGMENTS):
            return
        # docstrings / bare string statements carry documentation, not
        # behavior — a name drifting there can't break the cluster
        parent = self.parents.get(node)
        if isinstance(parent, ast.Expr):
            return
        self.emit(
            node, "name-literal",
            f"name literal {node.value!r} — use "
            "instaslice_tpu/api/constants.py",
        )

    def _check_defaults(self, node) -> None:
        args = node.args
        for default in list(args.defaults) + [
            d for d in args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.emit(default, "mutable-default")
            elif (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                self.emit(default, "mutable-default")


# ----------------------------------------------------------------- API


def lint_file(path: str, display_path: Optional[str] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return _Linter(path, display_path or path, source).run()


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        if any(path.endswith(skip) for skip in SKIP_FILES):
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        display = rel if not rel.startswith("..") else path
        findings.extend(lint_file(path, display))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="slicelint", description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*", help="files or directories "
                    "(default: instaslice_tpu + tools)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rule, desc in RULES.items():
            print(f"{rule}: {desc}")
        return 0
    paths = args.paths or [
        os.path.join(_REPO_ROOT, "instaslice_tpu"),
        os.path.join(_REPO_ROOT, "tools"),
    ]
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(
            f"slicelint: {len(findings)} finding(s) — fix, or suppress "
            "a justified site with '# slicelint: disable=<rule>'",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
