"""Benchmark entry: control-plane grant latency + on-chip workload numbers.

Headline (BASELINE.md): slice-grant p50 latency (request → pod Running),
target < 60 s for a dynamically carved slice (the reference publishes no
numbers at all — its only anecdote is a 15 s gated-pod→Running AGE in a
demo transcript, ``/root/reference/README.md:200-203``). This drives the
full control loop — gated pod → controller placement → CR fan-out → agent
realization on the device backend → ConfigMap handoff → ungate →
scheduler bind — on a simulated two-node v5e-16 torus under a
mixed-profile load, and reports the p50 over all grants.

Secondary (BASELINE.md "measure & report"): decode tokens/sec/chip, train
MFU, and the compiled pallas flash kernel vs XLA — measured on the real
chip by ``instaslice_tpu/bench_tpu.py``. Each phase runs in ITS OWN
subprocess with its own timeout, cheapest first, and its JSON fragment is
merged (and echoed to stderr) the moment it lands — a hang in one phase
costs only that phase's numbers. A persistent XLA compilation cache is
shared across the phase subprocesses so re-runs skip the 20-40 s first
compiles. A missing or hung TPU is a REPORTED per-phase error in the
output (``tpu_<phase>_error``), never a silent CPU fallback.

Prints ONE JSON line. The required keys ({"metric", "value", "unit",
"vs_baseline"}) carry the headline; the TPU numbers ride alongside.
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import subprocess
import sys
import time

BASELINE_S = 60.0
# mixed load from BASELINE.json configs[3]: 8 concurrent pods, mixed
# {1x1, 2x1, 2x2} on one v5e-16 (two hosts, 4x4 torus); run 3 waves.
# 14 of 16 chips per wave — concurrent but not a perfect-packing puzzle.
WAVE = ["v5e-2x2", "v5e-2x1", "v5e-2x1", "v5e-2x1",
        "v5e-1x1", "v5e-1x1", "v5e-1x1", "v5e-1x1"]
WAVES = 3

#: total wall budget for the on-chip half; first compiles are ~20-40 s.
TPU_BENCH_TIMEOUT = float(os.environ.get("TPUSLICE_TPU_BENCH_TIMEOUT", "870"))

#: (phase, per-phase cap seconds) in PRIORITY order under the shared
#: budget — probe is a tiny compile that proves the chip answers before
#: anything expensive runs; then the VERDICT-required numbers (flash
#: fwd/bwd, batch-32 + int8 serving, MFU sweep, 7B-class serving), then
#: the nice-to-haves. A cold compile cache can exhaust the budget
#: mid-list; this order decides what a short day still records.
TPU_PHASES = [
    ("probe", 120.0),
    ("flash_fwd", 180.0),
    ("flash_bwd", 240.0),
    ("serving", 300.0),
    ("serving_quant", 300.0),
    ("mfu", 300.0),
    ("serving_7b", 420.0),
    ("serving_spec", 300.0),
    ("serving_small", 180.0),
    ("serving_tp", 120.0),
]


def bench_control_plane(transport: str = "inproc") -> float:
    """Slice-grant p50 over 3 mixed waves on the 2-node sim. Pure control
    plane — no jax, no chip. ``transport="http"`` runs the same waves
    with the controller, both agents, and the submitter each on their own
    real-HTTP connection to the served fake API (URL building, JSON
    verbs, streaming watches — everything but a real etcd/scheduler)."""
    from instaslice_tpu.sim import SimCluster

    grants = []
    with SimCluster(n_nodes=2, generation="v5e",
                    deletion_grace_seconds=0.2, transport=transport) as c:
        for wave in range(WAVES):
            names = []
            t0 = {}
            for i, profile in enumerate(WAVE):
                name = f"bench-{wave}-{i}"
                t0[name] = time.monotonic()
                c.submit(name, profile=profile)
                names.append(name)
            for name in names:
                if not c.wait_phase(name, "Running", timeout=90):
                    raise RuntimeError(
                        f"{name} never reached Running "
                        f"(phase={c.pod_phase(name)})"
                    )
                grants.append(time.monotonic() - t0[name])
            for name in names:
                c.delete_pod(name)
            for name in names:
                c.wait_gone(name, timeout=60)
    return statistics.median(grants)


def _run_tpu_phase(phase: str, timeout: float, env: dict) -> dict:
    """One phase in its own subprocess; returns its JSON fragment or a
    ``{"error": ...}`` fragment for timeouts / crashes / no-JSON.

    Timeout is enforced SIGINT-first: hard-killing a TPU claimant leaves
    a stale remote claim that wedges the tunnel for hours
    (``docs/PERF.md``), so a stuck phase first gets a KeyboardInterrupt
    and a grace window to unwind its backend before SIGKILL."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "instaslice_tpu.bench_tpu",
         "--phase", phase],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        proc = subprocess.CompletedProcess(
            proc.args, proc.returncode, stdout, stderr
        )
    except subprocess.TimeoutExpired:
        how = "SIGINT"
        proc.send_signal(signal.SIGINT)
        try:
            proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            how = "SIGKILL (ignored SIGINT for 20s)"
            proc.kill()
            proc.communicate()
        return {"error": (
            f"phase exceeded its {timeout:.0f}s cap, stopped via {how} "
            "(chip unreachable, tunnel hung, or compile too slow)"
        )}
    out: dict = {}
    parsed = False
    lines = (proc.stdout or b"").decode().strip().splitlines()
    for line in reversed(lines):  # last JSON line wins; skip stray prints
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict):  # bare scalars ('0', 'null') also parse
            out = cand
            parsed = True
            break
    if not parsed:
        out["error"] = (
            f"phase emitted no JSON (rc={proc.returncode}): "
            + (proc.stderr or proc.stdout or b"").decode()[-300:]
        )
    elif proc.returncode != 0 and "error" not in out:
        out["error"] = (
            (proc.stderr or b"").decode()[-300:].strip()
            or f"phase exited rc={proc.returncode} with no stderr"
        )
    return out


def bench_tpu() -> dict:
    """Run each on-chip phase in its own subprocess under its own cap and
    a shared total budget. Fragments merge incrementally; per-phase
    failures land as ``tpu_<phase>_error`` keys so one hung phase cannot
    forfeit the others' numbers (the round-2 failure mode)."""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(here, ".jax_cache")
    )
    deadline = time.monotonic() + TPU_BENCH_TIMEOUT
    out: dict = {}
    for phase, cap in TPU_PHASES:
        remaining = deadline - time.monotonic()
        if remaining < 15:
            out[f"tpu_{phase}_error"] = (
                f"skipped: total bench budget ({TPU_BENCH_TIMEOUT:.0f}s) "
                "exhausted by earlier phases"
            )
            continue
        frag = _run_tpu_phase(phase, min(cap, remaining), env)
        err = frag.pop("error", None)
        out.update(frag)
        if err is not None:
            err = err or "phase failed with empty error message"
            out[f"tpu_{phase}_error"] = err
            print(f"[bench] {phase}: ERROR {err}", file=sys.stderr)
            if phase == "probe":
                # the probe exists so a dead/missing chip fails CHEAPLY;
                # grinding the expensive phases against it would just
                # drain the budget into guaranteed timeouts
                out["tpu_error"] = err
                for rest, _ in TPU_PHASES:
                    if rest != "probe" and f"tpu_{rest}_error" not in out:
                        out[f"tpu_{rest}_error"] = (
                            "skipped: probe failed (chip dead or "
                            "unreachable)"
                        )
                break
        else:
            print(f"[bench] {phase}: {json.dumps(frag)}", file=sys.stderr)
    return out


def main() -> int:
    try:
        p50 = bench_control_plane()
    except Exception as e:
        print(f"FATAL: control-plane bench failed: {e}", file=sys.stderr)
        return 1

    result = {
        "metric": "slice_grant_p50_latency",
        "value": round(p50, 4),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_S / p50, 1) if p50 > 0 else 0,
    }
    try:
        http_p50 = bench_control_plane(transport="http")
        result["slice_grant_p50_latency_http"] = round(http_p50, 4)
    except Exception as e:  # noqa: BLE001 - report alongside, don't kill
        result["slice_grant_http_error"] = f"{type(e).__name__}: {e}"
    result.update(bench_tpu())
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
