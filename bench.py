"""Headline benchmark: slice-grant p50 latency (request → pod Running).

BASELINE.md target: < 60 s for a dynamically carved slice (the reference
publishes no numbers at all — its only anecdote is a 15 s gated-pod→Running
AGE in a demo transcript, ``/root/reference/README.md:200-203``). This
drives the full control loop — gated pod → controller placement → CR
fan-out → agent realization on the device backend → ConfigMap handoff →
ungate → scheduler bind — on a simulated two-node v5e-16 torus under a
mixed-profile load, and reports the p50 over all grants.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
``vs_baseline`` is baseline/value (>1 = faster than the 60 s target).
"""

from __future__ import annotations

import json
import statistics
import sys
import time

BASELINE_S = 60.0
# mixed load from BASELINE.json configs[3]: 8 concurrent pods, mixed
# {1x1, 2x1, 2x2} on one v5e-16 (two hosts, 4x4 torus); run 3 waves.
# 14 of 16 chips per wave — concurrent but not a perfect-packing puzzle.
WAVE = ["v5e-2x2", "v5e-2x1", "v5e-2x1", "v5e-2x1",
        "v5e-1x1", "v5e-1x1", "v5e-1x1", "v5e-1x1"]
WAVES = 3


def main() -> int:
    from instaslice_tpu.sim import SimCluster

    grants = []
    with SimCluster(n_nodes=2, generation="v5e",
                    deletion_grace_seconds=0.2) as c:
        for wave in range(WAVES):
            names = []
            t0 = {}
            for i, profile in enumerate(WAVE):
                name = f"bench-{wave}-{i}"
                t0[name] = time.monotonic()
                c.submit(name, profile=profile)
                names.append(name)
            for name in names:
                if not c.wait_phase(name, "Running", timeout=90):
                    print(
                        f"FATAL: {name} never reached Running "
                        f"(phase={c.pod_phase(name)})",
                        file=sys.stderr,
                    )
                    return 1
                grants.append(time.monotonic() - t0[name])
            for name in names:
                c.delete_pod(name)
            for name in names:
                c.wait_gone(name, timeout=60)

    p50 = statistics.median(grants)
    print(json.dumps({
        "metric": "slice_grant_p50_latency",
        "value": round(p50, 4),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_S / p50, 1) if p50 > 0 else 0,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
