"""Benchmark entry: control-plane grant latency + on-chip workload numbers.

Headline (BASELINE.md): slice-grant p50 latency (request → pod Running),
target < 60 s for a dynamically carved slice (the reference publishes no
numbers at all — its only anecdote is a 15 s gated-pod→Running AGE in a
demo transcript, ``/root/reference/README.md:200-203``). This drives the
full control loop — gated pod → controller placement → CR fan-out → agent
realization on the device backend → ConfigMap handoff → ungate →
scheduler bind — on a simulated two-node v5e-16 torus under a
mixed-profile load, and reports the p50 over all grants.

Secondary (BASELINE.md "measure & report"): decode tokens/sec/chip, train
MFU, and the compiled pallas flash kernel vs XLA — measured on the real
chip by ``instaslice_tpu/bench_tpu.py``. Each phase runs in ITS OWN
subprocess with its own timeout, cheapest first, and its JSON fragment is
merged (and echoed to stderr) the moment it lands — a hang in one phase
costs only that phase's numbers. A persistent XLA compilation cache is
shared across the phase subprocesses so re-runs skip the 20-40 s first
compiles. A missing or hung TPU is a REPORTED per-phase error in the
output (``tpu_<phase>_error``), never a silent CPU fallback.

Prints ONE JSON line. The required keys ({"metric", "value", "unit",
"vs_baseline"}) carry the headline; the TPU numbers ride alongside.
"""

from __future__ import annotations

import argparse
import contextlib
import datetime
import fcntl
import json
import os
import signal
import statistics
import subprocess
import sys
import threading
import time

BASELINE_S = 60.0

_HERE = os.path.dirname(os.path.abspath(__file__))

#: incremental on-chip results store, shared by every capture path: the
#: normal bench run and the ``--watchdog`` both persist each phase's
#: fragment here the moment it lands, so a mid-run wedge keeps
#: everything already measured, and a recovery window between runs
#: accumulates coverage. The normal run folds this store into its output
#: when its own probe fails — numbers captured earlier in the round
#: still reach the driver's artifact (with provenance).
RESULTS_STORE = os.environ.get(
    "TPUSLICE_BENCH_STORE", os.path.join(_HERE, "BENCH_TPU_RESULTS.json")
)

#: chip-health journal: one JSON line {ts, alive, rtt_ms|error} per
#: probe, appended by the watchdog (and by normal runs' probe phase) —
#: the committed evidence of when the tunnel answered this round.
HEALTH_JOURNAL = os.environ.get(
    "TPUSLICE_TPU_HEALTH_JOURNAL", os.path.join(_HERE, "TPU_HEALTH.jsonl")
)
# mixed load from BASELINE.json configs[3]: 8 concurrent pods, mixed
# {1x1, 2x1, 2x2} on one v5e-16 (two hosts, 4x4 torus); run 3 waves.
# 14 of 16 chips per wave — concurrent but not a perfect-packing puzzle.
WAVE = ["v5e-2x2", "v5e-2x1", "v5e-2x1", "v5e-2x1",
        "v5e-1x1", "v5e-1x1", "v5e-1x1", "v5e-1x1"]
WAVES = 3

#: total wall budget for the on-chip half; first compiles are ~20-40 s.
TPU_BENCH_TIMEOUT = float(os.environ.get("TPUSLICE_TPU_BENCH_TIMEOUT", "870"))

#: (phase, per-phase cap seconds) in PRIORITY order under the shared
#: budget — probe is a tiny compile that proves the chip answers before
#: anything expensive runs; then the VERDICT-required numbers (flash
#: fwd/bwd, batch-32 + int8 serving, MFU sweep, 7B-class serving), then
#: the nice-to-haves. A cold compile cache can exhaust the budget
#: mid-list; this order decides what a short day still records.
TPU_PHASES = [
    ("probe", 120.0),
    ("flash_fwd", 180.0),
    ("flash_bwd", 240.0),
    ("serving", 300.0),
    ("serving_quant", 300.0),
    ("mfu", 300.0),
    ("serving_7b", 420.0),
    ("serving_lora", 300.0),
    ("serving_spec", 300.0),
    ("serving_small", 180.0),
    ("serving_tp", 120.0),
    # moe LAST in both orderings (here and WATCHDOG_PRIORITY): it is
    # the slowest phase (two fresh model compiles), and a slow phase
    # early in a shared-budget sequence starves everything behind it —
    # the 2026-07-31 lesson, where three watchdog bursts died at moe
    # with four phases never attempted
    ("moe", 480.0),
]


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


def _grant_stats(grants, wall_seconds: float) -> dict:
    """The shared BENCH_LOCAL_* result shape for any grant latency
    sample: p50/p95/p99 + grants/sec — one format for the 2-node
    headline waves and the 1k-node scale tier (docs/SCALING.md)."""
    s = sorted(grants)
    return {
        "grants": len(s),
        "p50_s": round(statistics.median(s), 4) if s else 0.0,
        "p95_s": round(_percentile(s, 0.95), 4),
        "p99_s": round(_percentile(s, 0.99), 4),
        "grants_per_sec": (
            round(len(s) / wall_seconds, 2) if wall_seconds > 0 else 0.0
        ),
    }


def bench_control_plane(transport: str = "inproc") -> dict:
    """Slice-grant latency stats over 3 mixed waves on the 2-node sim
    (:func:`_grant_stats` shape — p50 is the headline, p95/p99 and
    grants/sec ride along). Pure control plane — no jax, no chip.
    ``transport="http"`` runs the same waves with the controller, both
    agents, and the submitter each on their own real-HTTP connection to
    the served fake API (URL building, JSON verbs, streaming watches —
    everything but a real etcd/scheduler)."""
    from instaslice_tpu.sim import SimCluster

    grants = []
    bench_t0 = time.monotonic()
    with SimCluster(n_nodes=2, generation="v5e",
                    deletion_grace_seconds=0.2, transport=transport) as c:
        for wave in range(WAVES):
            names = []
            t0 = {}
            for i, profile in enumerate(WAVE):
                name = f"bench-{wave}-{i}"
                t0[name] = time.monotonic()
                c.submit(name, profile=profile)
                names.append(name)
            for name in names:
                if not c.wait_phase(name, "Running", timeout=90):
                    raise RuntimeError(
                        f"{name} never reached Running "
                        f"(phase={c.pod_phase(name)})"
                    )
                grants.append(time.monotonic() - t0[name])
            for name in names:
                c.delete_pod(name)
            for name in names:
                c.wait_gone(name, timeout=60)
    return _grant_stats(grants, time.monotonic() - bench_t0)


def bench_scale(
    n_nodes: int = 1000,
    n_pods: int = 2000,
    nodes_per_group: int = 2,
    baseline: bool = False,
    profile: str = "v5e-1x1",
    timeout: float = 900.0,
    agent_workers: int = 8,
) -> dict:
    """Fleet-scale grants/sec: ``n_pods`` single-host pods against an
    ``n_nodes`` sim split into ``nodes_per_group``-host torus groups,
    driven by the fleet agent manager. Reports gate→ungate p50/p95/p99
    (an ungate watcher timestamps the moment each pod's scheduling gate
    comes off — the controller's half of the grant, independent of the
    simulated kubelet bind) and grants/sec over the whole burst, plus
    the controller's reconcile/error counters and the hot span p50s from
    the trace profiler (which is how the informer/coalescing wins were
    attributed — docs/SCALING.md).

    ``baseline=True`` measures the pre-informer serial control plane
    (full re-list per reconcile, one worker, uncoalesced writes) for
    the before/after ratio."""
    from instaslice_tpu.sim import SimCluster
    from instaslice_tpu.utils.trace import get_tracer, reset_tracer

    reset_tracer()
    ungated_at: dict = {}
    submitted_at: dict = {}
    stop = threading.Event()

    def watch_ungates(kube) -> None:
        # one clean watch on Pods: record the first event showing a
        # bench pod without its scheduling gate
        while not stop.is_set():
            try:
                for event, obj in kube.watch(
                    "Pod", replay=True, timeout=0.25
                ):
                    if stop.is_set():
                        return
                    if event in ("BOOKMARK", "DELETED"):
                        continue
                    md = obj.get("metadata", {})
                    name = md.get("name", "")
                    if name not in submitted_at or name in ungated_at:
                        continue
                    if not obj.get("spec", {}).get("schedulingGates"):
                        ungated_at[name] = time.monotonic()
            except Exception as e:  # pragma: no cover - observer only
                print(f"[scale] ungate watcher: {e}", file=sys.stderr)
                stop.wait(0.1)

    sim = SimCluster(
        n_nodes=n_nodes,
        generation="v5e",
        nodes_per_group=nodes_per_group,
        fleet_agents=True,
        agent_workers=agent_workers,
        workers=1 if baseline else None,
        use_cache=not baseline,
        deletion_grace_seconds=0.2,
        health_interval=0,
    )
    t_start = time.monotonic()
    with sim as c:
        watcher = threading.Thread(
            target=watch_ungates, args=(c.backing,), daemon=True
        )
        for i in range(n_pods):
            name = f"scale-{i}"
            submitted_at[name] = time.monotonic()
            c.submit(name, profile=profile)
        watcher.start()
        deadline = time.monotonic() + timeout
        while (
            len(ungated_at) < n_pods and time.monotonic() < deadline
        ):
            time.sleep(0.25)
        stop.set()
        done = dict(ungated_at)
        wall = (max(done.values()) - t_start) if done else 0.0
        grants = [done[n] - submitted_at[n] for n in done]
        out = _grant_stats(grants, wall)
        out.update({
            "n_nodes": n_nodes,
            "n_pods": n_pods,
            "nodes_per_group": nodes_per_group,
            "mode": "baseline-serial-relist" if baseline else "informer",
            "completed": len(done),
            "wall_s": round(wall, 2),
            "reconciles": c.controller.manager.reconcile_count,
            "reconcile_errors": c.controller.manager.error_count,
            "kube_requests": getattr(c.backing, "request_count", None),
        })
        if not baseline and c.controller._cr_writer is not None:
            w = c.controller._cr_writer
            out["cr_write_ops"] = w.ops
            out["cr_write_commits"] = w.commits
        spans = {}
        summary = get_tracer().summary()
        for name in ("controller.reconcile", "controller.allocate",
                     "controller.place", "controller.ungate",
                     "agent.realize"):
            if name in summary:
                spans[name] = summary[name]
        out["span_summary"] = spans
        if len(done) < n_pods:
            out["error"] = (
                f"only {len(done)}/{n_pods} pods ungated within "
                f"{timeout:.0f}s"
            )
    return out


def bench_defrag(
    policy: str = "frag-aware",
    repack: bool = True,
    seed: int = 7,
    groups: int = 2,
    hosts_per_group: int = 2,
    churn_rounds: int = 2,
    big_per_group: int = 2,
    timeout: float = 45.0,
    chaos: bool = False,
) -> dict:
    """Defragmentation tier: a seeded churny multi-profile workload that
    fragments the torus, then measures whether big requests recover.

    Phases (all on a ``groups`` x ``hosts_per_group``-host v5e sim):

    1. **fill** — 1x1 fillers on every chip;
    2. **churn** — ``churn_rounds`` of: delete a seeded-random third of
       the fillers, push short-lived 2x1 pods through the holes, then
       refill to capacity (multi-profile churn scrambles placement
       history exactly the way ROADMAP item 1 describes);
    3. **carve** — keep one seeded-random filler per 2x2-aligned quad
       and delete the rest: every quad blocked, ~75% of chips free,
       zero 2x2 anchors — the canonical stranded-capacity state;
    4. **measure** — submit 2x2 pods and record NoCapacity wait per pod
       (censored at ``timeout`` for pods never granted) plus the
       capacity-utilization timeline.

    With ``repack=True`` the sim runs the defragmentation loop; with
    ``chaos=True`` every node's backend fails its next chip reservation,
    so the first migration's destination realize fails mid-flight and
    must roll back cleanly before the retry lands. Every journal event
    of the run is chain-checked strictly (``tools/validate_events``) —
    an illegal migration transition fails the tier, not just the gate.
    """
    import random

    from instaslice_tpu.obs.journal import (
        Journal,
        get_journal,
        reset_journal,
    )
    from instaslice_tpu.sim import SimCluster
    from instaslice_tpu.topology.placement import Box

    tools_dir = os.path.join(_HERE, "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import validate_events

    rng = random.Random(seed)
    reset_journal(Journal(capacity=65536))
    n_nodes = groups * hosts_per_group
    total_chips = n_nodes * 8  # v5e: 8 chips/host
    t_bench = time.monotonic()
    util_samples: list = []
    try:
        with SimCluster(
            n_nodes=n_nodes, generation="v5e",
            nodes_per_group=hosts_per_group,
            policy=policy, repack=repack,
            repack_interval=0.1, repack_cooldown=0.4,
            repack_max_concurrent=4,
            deletion_grace_seconds=0.2, health_interval=0,
        ) as c:

            def occupied() -> int:
                seen = {}
                for m in c.kube.list("TpuSlice", namespace=c.namespace):
                    for aid, a in m["spec"].get(
                        "allocations", {}
                    ).items():
                        if a.get("status") != "deleted":
                            seen[aid] = a["box"]
                return sum(
                    Box.from_key(b).chip_count for b in seen.values()
                )

            def must_run(name: str, deadline_s: float = 60.0) -> None:
                if not c.wait_phase(name, "Running", timeout=deadline_s):
                    raise RuntimeError(
                        f"{name} never reached Running "
                        f"(phase={c.pod_phase(name)})"
                    )

            quiet = threading.Event()

            def quiesce_repacker(deadline_s: float = 15.0) -> None:
                # a migration in its erase→re-grant window holds no
                # allocation record, so occupied() undercounts by the
                # migrating chips; counting free capacity (the refill
                # sizing below) while a migration is in flight would
                # over-submit unsatisfiable fillers
                if c.repacker is None:
                    return
                deadline = time.monotonic() + deadline_s
                while c.repacker._active and time.monotonic() < deadline:
                    quiet.wait(0.05)

            # ---- 1. fill
            fillers = []
            for i in range(total_chips):
                name = f"fill-{i}"
                c.submit(name, profile="v5e-1x1")
                fillers.append(name)
            for name in fillers:
                must_run(name)

            # ---- 2. churn
            for r in range(churn_rounds):
                victims = rng.sample(fillers, k=len(fillers) // 3)
                for v in victims:
                    c.delete_pod(v)
                for v in victims:
                    c.wait_gone(v, timeout=30)
                fillers = [f for f in fillers if f not in victims]
                transients = [
                    f"churn-{r}-{i}" for i in range(groups * 2)
                ]
                for name in transients:
                    c.submit(name, profile="v5e-2x1")
                for name in transients:
                    # best-effort: scattered holes may strand a 2x1 —
                    # that blockage is itself churn (and, with the
                    # repacker on, real work for it)
                    c.wait_phase(name, "Running", timeout=3)
                for name in transients:
                    c.delete_pod(name)
                for name in transients:
                    c.wait_gone(name, timeout=30)
                quiesce_repacker()
                refill = [
                    f"fill-{r}x{i}"
                    for i in range(total_chips - occupied())
                ]
                for name in refill:
                    c.submit(name, profile="v5e-1x1")
                for name in refill:
                    must_run(name)
                fillers.extend(refill)

            # ---- 3. carve: one survivor per 2x2-aligned quad
            quiesce_repacker()  # pod→box map must not race a migration
            pod_quad = {}
            for aid, a in c.allocations().items():
                if a.get("status") == "deleted":
                    continue
                box = Box.from_key(a["box"])
                quad = (
                    a.get("torusGroup", ""),
                    box.anchor[0] // 2 * 2,
                    box.anchor[1] // 2 * 2,
                )
                for p in a.get("pods", []):
                    pod_quad[p["podName"]] = quad
            by_quad: dict = {}
            for name in fillers:
                quad = pod_quad.get(name)
                if quad is not None:
                    by_quad.setdefault(quad, []).append(name)
            doomed = []
            for quad, names in sorted(by_quad.items()):
                keep = rng.choice(sorted(names))
                doomed.extend(n for n in names if n != keep)
            for name in doomed:
                c.delete_pod(name)
            for name in doomed:
                c.wait_gone(name, timeout=30)
            util_carved = occupied() / total_chips

            # ---- 4. the blocked big requests
            if chaos:
                # fail each node's NEXT chip reservation: the first
                # migration to land on any node dies mid-flight and
                # must roll back through _mark_deleted
                for node in list(c.backends):
                    c.backends[node].inject_failures("reserve", 1)
            bigs = [
                f"big-{i}" for i in range(groups * big_per_group)
            ]
            t0 = {}
            for name in bigs:
                t0[name] = time.monotonic()
                c.submit(name, profile="v5e-2x2")
            done: dict = {}
            deadline = time.monotonic() + timeout
            pacer = threading.Event()
            while time.monotonic() < deadline and len(done) < len(bigs):
                for name in bigs:
                    if name not in done and \
                            c.pod_phase(name) == "Running":
                        done[name] = time.monotonic() - t0[name]
                util_samples.append(occupied() / total_chips)
                pacer.wait(0.05)
            util_after = occupied() / total_chips
            waits = sorted(done.get(n, timeout) for n in bigs)
            out = {
                "policy": policy,
                "repack": repack,
                "chaos": chaos,
                "seed": seed,
                "groups": groups,
                "hosts_per_group": hosts_per_group,
                "total_chips": total_chips,
                "churn_rounds": churn_rounds,
                "util_carved": round(util_carved, 4),
                "util_after": round(util_after, 4),
                "util_peak": round(max(util_samples), 4)
                if util_samples else round(util_after, 4),
                "big_pods": len(bigs),
                "big_granted": len(done),
                "nocap_wait_censored": len(done) < len(bigs),
                "nocap_wait_p50_s": round(
                    statistics.median(waits), 3
                ) if waits else 0.0,
                "nocap_wait_p95_s": round(
                    _percentile(waits, 0.95), 3
                ),
                "reconcile_errors": c.controller.manager.error_count,
                "wall_s": round(time.monotonic() - t_bench, 1),
            }
            if c.repacker is not None:
                out["migrations_done"] = c.repacker.migrations_done
                out["migrations_failed"] = c.repacker.migrations_failed
                out["repack_plans"] = c.repacker.plans
        events = [e.to_dict() for e in get_journal().events()]
        out["journal_events"] = len(events)
        out["chain_errors"] = validate_events.check_chains(
            events, strict=True
        )
    finally:
        reset_journal()
    return out


def smoke_defrag(floor: float = 0.5) -> int:
    """``make bench-defrag-smoke``: a <60 s single-group churn run
    gating the fast tier — asserts the repacker recovers a utilization
    floor, grants every blocked big pod, and keeps every allocation
    epoch (including migration epochs) a legal journaled transition
    chain under the strict events-check validator."""
    out = bench_defrag(
        policy=os.environ.get("TPUSLICE_DEFRAG_POLICY", "frag-aware"),
        repack=True,
        seed=int(os.environ.get("TPUSLICE_DEFRAG_SEED", "7")),
        groups=1, hosts_per_group=2, churn_rounds=1, big_per_group=2,
        timeout=40.0,
    )
    print(json.dumps(out))
    failures = []
    if out["big_granted"] < out["big_pods"]:
        failures.append(
            f"only {out['big_granted']}/{out['big_pods']} blocked pods "
            "granted — the repacker never cleared the stranded capacity"
        )
    if out["util_after"] < floor:
        failures.append(
            f"utilization {out['util_after']} below floor {floor}"
        )
    if out.get("migrations_done", 0) < 1:
        failures.append("no completed migrations — repacker idle")
    if out["chain_errors"]:
        failures.append(
            f"illegal transition chains: {out['chain_errors'][:3]}"
        )
    if out["reconcile_errors"]:
        failures.append(
            f"{out['reconcile_errors']} reconcile error(s)"
        )
    for f in failures:
        print(f"bench-defrag-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


#: the mixed-SLO tenant scenario both serving arms run: a weighted
#: latency tenant with a real TTFT target, a standard tenant, and a
#: best-effort tenant the scheduler may preempt/degrade. ONE spec
#: string — the server policy and the loadgen traffic mix share it
#: (serving/scheduler.py grammar).
SERVING_TENANTS = (
    "gold:3:latency:2.0,silver:2:standard,bronze:1:best-effort:30"
)


def bench_serving(
    mode: str = "continuous",
    requests: int = 48,
    concurrency: int = 12,
    prompt_len: int = 24,
    max_tokens: int = 32,
    jitter: float = 0.9,
    seed: int = 9,
    max_batch: int = 8,
    block_size: int = 16,
    d_model: int = 128,
    prefill_len: int = 8,
    engine_opts: dict = None,
    overlap: bool = None,
    engine_factory=None,
) -> dict:
    """One serving-scheduler arm (docs/SERVING.md "Continuous batching
    & tenant SLOs"): a CPU-sized engine behind the real ApiServer, a
    mixed-SLO multi-tenant loadgen run at mixed sequence lengths, and
    a sampler thread reading /v1/stats so paged kv utilization is
    measured UNDER load, not at the idle end.

    ``mode="fixed"`` is the classic static-batching baseline the
    continuous scheduler is judged against (ROADMAP item 3's "fixed
    decode rounds"): FIFO admission with head-of-line blocking and
    full ``block_size`` decode rounds regardless of per-request
    budgets — requests that finish mid-round hold their slot (and
    their blocks) to the round's end. The loop this PR replaced
    already trimmed rounds to budgets, so the ratio below isolates
    the cost of fixed rounds themselves, not a literal before/after
    of one commit."""
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.metrics.metrics import ServingMetrics
    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.obs.journal import Journal, get_journal, \
        reset_journal
    from instaslice_tpu.obs.profiler import Profiler, get_profiler, \
        reset_profiler
    from instaslice_tpu.serving import ServingEngine
    from instaslice_tpu.serving.api_server import ApiServer
    from instaslice_tpu.serving.loadgen import run as loadgen_run

    reset_journal(Journal(capacity=65536))
    # per-arm profile artifact (tools/bench_trend.py learns per-segment
    # p95 keys from it): an armed, arm-private profiler ring
    reset_profiler(Profiler(armed=True))
    # heavy enough that a decode STEP costs real compute relative to a
    # dispatch — the regime real serving lives in (decode is HBM/FLOP
    # bound at batch); a micro-model would make wasted slot-steps look
    # free and reward exactly the wrong scheduler
    if engine_factory is not None:
        # the spec tier supplies its own draft/target pair (and
        # temperature) — everything downstream (server, loadgen,
        # ledgers) is shared
        eng = engine_factory(max_batch=max_batch, max_len=128,
                             prefill_len=prefill_len, kv_block_size=16)
    else:
        cfg = ModelConfig(
            vocab_size=128, d_model=d_model, n_heads=4, n_layers=4,
            d_ff=4 * d_model, dtype=jnp.float32, remat=False,
        )
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        eng = ServingEngine(model, params, max_batch=max_batch,
                            max_len=128, prefill_len=prefill_len,
                            kv_block_size=16, **(engine_opts or {}))
    # compile every prefill-batch bucket (and, with a draft, the full
    # spec draft/verify shape set) OUT of the measured window: the
    # loadgen warm-up's burst widths are traffic-dependent, and one
    # cold compile mid-run swamps a seconds-long CPU measurement
    eng.warm_prefill_buckets()
    eng.warm_spec_programs()
    metrics = ServingMetrics()
    samples: list = []
    stop = threading.Event()
    try:
        with ApiServer(eng, block_size=block_size, metrics=metrics,
                       tenants=SERVING_TENANTS, mode=mode,
                       preempt_margin=0.3, overlap=overlap,
                       request_timeout=180) as srv:

            def probe(path="/v1/stats"):
                import urllib.request

                with urllib.request.urlopen(srv.url + path,
                                            timeout=5) as r:
                    return json.loads(r.read())

            def sampler():
                while not stop.is_set():
                    try:
                        s = probe()
                        if s["live_slots"]:
                            samples.append((
                                s["kv"]["utilization"],
                                s["live_slots"],
                            ))
                    except Exception as e:  # pragma: no cover
                        print(f"[serving] sampler: {e}",
                              file=sys.stderr)
                    stop.wait(0.05)

            # warm the compiled prefill/decode programs out of the
            # measured window with an UNMEASURED burst of the same
            # traffic shape: both arms must be judged on scheduling,
            # not on who paid the jit compiles (CPU compiles dominate a
            # seconds-long run; the arms share a process, so without
            # this the second arm would free-ride the first's cache)
            loadgen_run(
                srv.url, requests=12, concurrency=4,
                prompt_len=prompt_len, max_tokens=max_tokens, vocab=128,
                stream=True, timeout=180, seed=seed + 1,
                tenants=SERVING_TENANTS, jitter=jitter,
            )
            warm_stats = srv.scheduler.stats()
            # the artifact reports the MEASURED window: drop the
            # warm-up burst's round records
            get_profiler().clear()
            t = threading.Thread(target=sampler, daemon=True)
            t.start()
            t0 = time.monotonic()
            report = loadgen_run(
                srv.url, requests=requests, concurrency=concurrency,
                prompt_len=prompt_len, max_tokens=max_tokens, vocab=128,
                stream=True, timeout=180, seed=seed,
                tenants=SERVING_TENANTS, jitter=jitter,
            )
            wall = time.monotonic() - t0
            stop.set()
            t.join(timeout=2)
            # counters are cumulative from server start: subtract the
            # warm-up burst so the arm reports ITS window only
            end = srv.scheduler.stats()
            stats = dict(end)
            for key in ("preempted", "resumed", "parked_shed",
                        "slo_misses"):
                stats[key] = end[key] - warm_stats[key]
            # preempt/resume ledger reconciliation after quiesce: the
            # scheduler's counters match the engine's, nothing is left
            # parked or holding KV blocks once every client got its
            # terminal response
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and (
                eng.slots or eng.parked
            ):
                time.sleep(0.02)
            ledger_ok = (
                srv.scheduler.preempted == eng.preempted_total
                and srv.scheduler.resumed == eng.resumed_total
                and not eng.parked and not eng.slots
                # post-quiesce every used block is the radix prefix
                # cache's (completions legitimately cache their KV)
                # and no dead rid pins a tree path
                and eng.kv.used_blocks() == eng.radix.pool_blocks()
                and not eng._radix_locks
            )
            profile_summary = get_profiler().segment_summary()
    finally:
        stop.set()
        reset_journal()
        reset_profiler()
    kv_util = [s[0] for s in samples]
    gold = report["tenants"]["gold"]
    bronze = report["tenants"]["bronze"]
    # compiled-program regression check rides every arm (the spec tier
    # gates on it: adaptive k must stay inside the documented shape set)
    budget = eng.compile_budget(block_cap=block_size)
    compiled = eng.compiled_programs()
    over = {k: (compiled[k], budget.get(k, 0)) for k in compiled
            if compiled[k] > budget.get(k, 0)}
    spec_block = {}
    if eng.draft_model is not None:
        w = warm_stats.get("spec", {})
        s = stats.get("spec", {})
        proposed = s.get("proposed", 0) - w.get("proposed", 0)
        accepted = s.get("accepted", 0) - w.get("accepted", 0)
        spec_block = {
            "spec_rounds": s.get("rounds", 0) - w.get("rounds", 0),
            "spec_proposed": proposed,
            "spec_accepted": accepted,
            "spec_acceptance_rate": round(accepted / proposed, 4)
            if proposed else 0.0,
            "spec_k": s.get("k", 0),
        }
    return {
        **spec_block,
        "compiled_over_budget": over,
        "mode": mode,
        "seed": seed,
        "requests": requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "jitter": jitter,
        "ok": report["ok"],
        "hung": report["outcomes"]["hung"],
        "errors": report["errors"],
        "wall_s": round(wall, 2),
        "client_tokens_per_sec": report["client_tokens_per_sec"],
        "ttft_p50_s": report["ttft_p50"],
        "ttft_p95_s": report["ttft_p95"],
        "gold_ttft_p95_s": gold["ttft_p95"],
        "gold_slo_attainment": gold.get("slo_attainment", 0.0),
        "gold_ttft_slo_s": gold.get("ttft_slo", 0.0),
        "bronze_ttft_p95_s": bronze["ttft_p95"],
        "tenants": report["tenants"],
        "kv_util_mean": round(
            statistics.mean(kv_util), 4
        ) if kv_util else 0.0,
        "kv_samples": len(samples),
        # warm-up-subtracted like the preempt/SLO counters above: the
        # arm reports ITS window, not the process totals
        "prefill_batches": (stats["engine"]["prefill_batches"]
                            - warm_stats["engine"]["prefill_batches"]),
        "prefill_rows": (stats["engine"]["prefill_rows"]
                         - warm_stats["engine"]["prefill_rows"]),
        "fastpath_rounds": (stats["engine"]["fastpath_rounds"]
                            - warm_stats["engine"]["fastpath_rounds"]),
        "preempted": stats["preempted"],
        "resumed": stats["resumed"],
        "parked_shed": stats["parked_shed"],
        "slo_misses": stats["slo_misses"],
        "ledger_ok": ledger_ok,
        # round-anatomy segment summary for the measured window
        # (obs/profiler.py): bench_trend gates per-segment p95 from it
        "profile": profile_summary,
    }


#: the bursty-admission mixed-SLO scenario the engine tier runs: high
#: loadgen concurrency (admission arrives in bursts) over prefill-heavy
#: prompts — the regime batched prefill + host/device overlap target
ENGINE_WORKLOAD = dict(
    mode="continuous", concurrency=16, prompt_len=48, max_tokens=24,
    jitter=0.6, prefill_len=8,
)


def bench_engine(optimized: bool = True, requests: int = 32,
                 seed: int = 10) -> dict:
    """One engine-tier arm (docs/SERVING.md "Engine hot path"): the
    same bursty-admission mixed-SLO workload over either the r10 hot
    path (batched prefill + single-adapter fast path + host/device
    overlap) or the PR 9 per-slot baseline (every admission its own
    dispatch chain, fully synchronous rounds) — same process, same
    scheduler policy, so the ratio isolates the dispatch shape."""
    out = bench_serving(
        requests=requests, seed=seed,
        engine_opts=(None if optimized else dict(
            batched_prefill=False, adapter_fastpath=False,
        )),
        overlap=optimized,
        **ENGINE_WORKLOAD,
    )
    out["arm"] = "optimized" if optimized else "per-slot"
    return out


def smoke_engine(floor: float = None) -> int:
    """``make bench-engine-smoke``: a <60 s bursty-admission run of
    BOTH engine arms in one process — asserts the hot-path arm
    sustains at least ``TPUSLICE_ENGINE_FLOOR`` times the per-slot
    baseline's tok/s, zero hung requests, and the preempt/resume
    ledger still reconciling on both arms."""
    if floor is None:
        floor = float(os.environ.get("TPUSLICE_ENGINE_FLOOR", "0.9"))
    reqs = int(os.environ.get("TPUSLICE_ENGINE_SMOKE_REQS", "20"))
    # floor 0.9 + best-of-3: the smoke is a REGRESSION gate on a
    # shared-core CI box where single runs of either arm swing ±30%
    # on OS noise — it catches a broken hot path (the bucket-compile
    # bug read 0.45x), not a 5% scheduling breeze. The recorded
    # `--engine` tier keeps the strict must-beat-on-both-axes gate.
    reps = max(1, int(os.environ.get(
        "TPUSLICE_ENGINE_SMOKE_REPEATS", "3")))
    # throwaway process-warming run: thread pools, sockets, allocator
    # — the first serving run in a process is slow for reasons neither
    # arm owns, and it must not land on either measured arm
    bench_engine(optimized=False, requests=6)
    bases, opts = [], []
    for _ in range(reps):
        bases.append(bench_engine(optimized=False, requests=reqs))
        opts.append(bench_engine(optimized=True, requests=reqs))
    base = max(bases, key=lambda r: r["client_tokens_per_sec"])
    opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
    print(json.dumps({"optimized": opt, "per_slot_baseline": base}))
    failures = []
    for arm in (base, opt):
        if arm["hung"]:
            failures.append(f"{arm['arm']}: {arm['hung']} hung")
        if arm["errors"]:
            failures.append(
                f"{arm['arm']}: {arm['errors']} loadgen error(s)"
            )
        if not arm["ledger_ok"]:
            failures.append(
                f"{arm['arm']}: preempt/resume ledger did not "
                "reconcile"
            )
    if opt["client_tokens_per_sec"] < floor * base[
            "client_tokens_per_sec"]:
        failures.append(
            f"hot path {opt['client_tokens_per_sec']} tok/s under "
            f"{floor}x the per-slot baseline "
            f"{base['client_tokens_per_sec']}"
        )
    if opt["prefill_batches"] == 0:
        failures.append("hot-path arm never dispatched a batched "
                        "prefill (knob wiring broken?)")
    for f in failures:
        print(f"bench-engine-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


def smoke_serving(slo_floor: float = 0.75, kv_floor: float = 0.5) -> int:
    """``make bench-serving-smoke``: a <60 s mixed-SLO loadgen run over
    the continuous scheduler gating the fast tier — asserts every
    request terminates, latency-class SLO attainment holds a floor,
    and paged kv utilization holds its floor."""
    out = bench_serving(
        mode="continuous",
        requests=int(os.environ.get("TPUSLICE_SERVING_SMOKE_REQS",
                                    "24")),
        concurrency=5,
        seed=int(os.environ.get("TPUSLICE_SERVING_SEED", "9")),
    )
    print(json.dumps(out))
    failures = []
    if out["hung"]:
        failures.append(f"{out['hung']} request(s) HUNG")
    if out["errors"]:
        failures.append(f"{out['errors']} loadgen error(s)")
    if out["gold_slo_attainment"] < slo_floor:
        failures.append(
            f"latency-class SLO attainment {out['gold_slo_attainment']}"
            f" below floor {slo_floor}"
        )
    if out["kv_util_mean"] < kv_floor:
        failures.append(
            f"kv utilization {out['kv_util_mean']} below floor "
            f"{kv_floor}"
        )
    for f in failures:
        print(f"bench-serving-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


#: the organic-prefix-sharing scenario the radix tier runs: prompts
#: draw their head from a small pool of shared prefixes (think common
#: system prompts across tenants), tails and budgets stay jittered —
#: nothing is registered, so the exact-match baseline re-prefills
#: every shared head while the radix arm stores it once and COW-forks
PREFIX_WORKLOAD = dict(
    concurrency=8, prompt_len=24, max_tokens=24, jitter=0.5,
    prefix_pool="4:96",
)


def bench_prefix(radix: bool = True, requests: int = 64,
                 seed: int = 11) -> dict:
    """One radix-prefix-cache arm (docs/SERVING.md "Radix prefix
    cache"): the shared-prefix loadgen workload over the real ApiServer
    with the radix cache on, or off (``--no-radix-cache`` — the
    exact-match-only PR 9 baseline, where organically shared prefixes
    are re-prefilled every time because nobody registered them).

    Both arms run the same warm-up burst first (compiles AND, for the
    radix arm, the steady-state tree the measured window serves from —
    a prefix cache is judged warm, like any cache tier), and both must
    quiesce with a clean ledger: no live/parked state, every pool
    block either free or held by the radix tree, no leaked path
    locks."""
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.metrics.metrics import ServingMetrics
    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.serving import ServingEngine
    from instaslice_tpu.serving.api_server import ApiServer
    from instaslice_tpu.serving.loadgen import run as loadgen_run

    cfg = ModelConfig(
        vocab_size=128, d_model=128, n_heads=4, n_layers=4,
        d_ff=512, dtype=jnp.float32, remat=False,
    )
    model = TpuLM(cfg)
    params = model.init(jax.random.key(0))
    eng = ServingEngine(model, params, max_batch=8, max_len=256,
                        prefill_len=16, kv_block_size=16,
                        radix_cache=radix)
    eng.warm_prefill_buckets()
    # compile the whole power-of-two decode-block set too: each
    # n_steps is its own program, the two arms' admission patterns
    # reach different n values, and ONE cold compile mid-run swamps a
    # seconds-long CPU measurement's TTFT tail (seen as a 1.2 s p95)
    eng.add_request([1, 2, 3])
    n = 1
    while n <= 16:
        eng.decode_block(n)
        n <<= 1
    for slot in list(eng.slots):
        eng.evict_slot(slot)
    metrics = ServingMetrics()
    workload = dict(PREFIX_WORKLOAD)
    pool = workload.pop("prefix_pool")
    # the same mixed-SLO tenant scenario the serving/engine tiers run:
    # shared prefixes ACROSS tenants is the motivating workload, and a
    # latency-class tenant makes the scheduler's round-shortening (and
    # so the TTFT axis) exercise the radix arm's faster admission
    with ApiServer(eng, block_size=16, metrics=metrics,
                   tenants=SERVING_TENANTS, preempt_margin=0.3,
                   request_timeout=180) as srv:
        # unmeasured warm burst: pays the jit compiles in both arms and
        # brings the radix arm to its steady state (tree populated)
        loadgen_run(
            srv.url, requests=10, concurrency=4, vocab=128,
            stream=True, timeout=180, seed=seed,
            prefix_pool=pool, tenants=SERVING_TENANTS,
            **{k: workload[k] for k in
               ("prompt_len", "max_tokens", "jitter")},
        )
        warm = srv.scheduler.stats()
        t0 = time.monotonic()
        report = loadgen_run(
            srv.url, requests=requests, vocab=128,
            stream=True, timeout=180, seed=seed,
            prefix_pool=pool, tenants=SERVING_TENANTS, **workload,
        )
        wall = time.monotonic() - t0
        # quiesce, then reconcile the ledger: nothing live or parked,
        # every used pool block is the radix tree's, zero leaked locks
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (eng.slots or eng.parked):
            time.sleep(0.02)
        stats = srv.scheduler.stats()
        budget = eng.compile_budget(block_cap=16)
        compiled = eng.compiled_programs()
        over = {k: (compiled[k], budget.get(k, 0)) for k in compiled
                if compiled[k] > budget.get(k, 0)}
        ledger_ok = (
            not eng.slots and not eng.parked
            and eng.kv.used_blocks() == eng.radix.pool_blocks()
            and not eng._radix_locks
            and not over
        )
    radix_stats = stats["radix"]
    warm_radix = warm["radix"]
    return {
        "arm": "radix" if radix else "exact-match-baseline",
        "seed": seed,
        "requests": requests,
        "prefix_pool": pool,
        "ok": report["ok"],
        "hung": report["outcomes"]["hung"],
        "errors": report["errors"],
        "wall_s": round(wall, 2),
        "client_tokens_per_sec": report["client_tokens_per_sec"],
        "ttft_p50_s": report["ttft_p50"],
        "ttft_p95_s": report["ttft_p95"],
        "client_reused_fraction":
            report["prefix_pool"]["reused_fraction"],
        # warm-up-subtracted: the arm reports ITS window only
        "prefix_hits": radix_stats["hits"] - warm_radix["hits"],
        "prefix_misses": radix_stats["misses"] - warm_radix["misses"],
        "prefix_inserted": (radix_stats["inserted"]
                            - warm_radix["inserted"]),
        "prefix_evicted": (radix_stats["evicted"]
                           - warm_radix["evicted"]),
        "prefix_tokens_saved": (radix_stats["tokens_saved"]
                                - warm_radix["tokens_saved"]),
        "radix_nodes": radix_stats["nodes"],
        "radix_blocks": radix_stats["blocks"],
        "compiled_over_budget": over,
        "ledger_ok": ledger_ok,
    }


def smoke_prefix(floor: float = None) -> int:
    """``make bench-prefix-smoke``: a <60 s shared-prefix run of BOTH
    arms — asserts the radix arm sustains at least
    ``TPUSLICE_PREFIX_FLOOR`` (default 0.9 — a REGRESSION gate like
    the engine smoke's, not a win gate: single short runs of either
    arm swing ±30% on the shared-core CI box, and the recorded
    ``--prefix`` tier keeps the strict must-beat-on-both-axes gate)
    times the exact-match baseline's tok/s with real prefix-hit token
    savings, zero hung requests, ledgers reconciling and zero leaked
    blocks after quiesce, and the compiled-program set inside the
    documented budget."""
    if floor is None:
        floor = float(os.environ.get("TPUSLICE_PREFIX_FLOOR", "0.9"))
    reqs = int(os.environ.get("TPUSLICE_PREFIX_SMOKE_REQS", "24"))
    reps = max(1, int(os.environ.get(
        "TPUSLICE_PREFIX_SMOKE_REPEATS", "3")))
    # throwaway process-warming run (see smoke_engine)
    bench_prefix(radix=False, requests=6)
    bases, opts = [], []
    for _ in range(reps):
        bases.append(bench_prefix(radix=False, requests=reqs))
        opts.append(bench_prefix(radix=True, requests=reqs))
    base = max(bases, key=lambda r: r["client_tokens_per_sec"])
    opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
    print(json.dumps({"radix": opt, "exact_match_baseline": base}))
    failures = []
    for arm in (base, opt):
        if arm["hung"]:
            failures.append(f"{arm['arm']}: {arm['hung']} hung")
        if arm["errors"]:
            failures.append(
                f"{arm['arm']}: {arm['errors']} loadgen error(s)"
            )
        if not arm["ledger_ok"]:
            failures.append(
                f"{arm['arm']}: ledger did not reconcile "
                f"(compiled over budget: {arm['compiled_over_budget']})"
            )
    if opt["client_tokens_per_sec"] < floor * base[
            "client_tokens_per_sec"]:
        failures.append(
            f"radix arm {opt['client_tokens_per_sec']} tok/s under "
            f"{floor}x the exact-match baseline "
            f"{base['client_tokens_per_sec']}"
        )
    if opt["prefix_tokens_saved"] <= 0:
        failures.append("radix arm saved zero prefix tokens "
                        "(cache wiring broken?)")
    if opt["prefix_hits"] <= 0:
        failures.append("radix arm never hit the cache")
    for f in failures:
        print(f"bench-prefix-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


#: the spec tier's workload: the same bursty mixed-SLO tenant traffic
#: as the engine tier, decode-heavy budgets (speculation pays on the
#: decode stream; prefill is untouched), run at temperature 0 AND >0 —
#: losslessness must not cost the sampled path its win
SPEC_WORKLOAD = dict(
    concurrency=8, prompt_len=24, max_tokens=32, jitter=0.6,
    prefill_len=8,
)


def _spec_model_pair(seed: int = 12, d_model: int = 128,
                     n_layers: int = 4, vocab: int = 128):
    """(target model, params, draft model, draft params) for the spec
    tier: the target's blocks past the first contribute EXACTLY zero
    to the residual stream (their attention/FF output projections are
    zeroed), and the draft IS the target's first block + shared
    embed/final-norm — so the draft agrees with the target almost
    everywhere at a quarter of the per-token cost. This reproduces the
    deployment regime speculative decoding targets (a distilled
    high-agreement draft) with constructed weights: the bench measures
    the ENGINE's round mechanics at a realistic acceptance rate, and
    the no-spec baseline serves the identical target at identical
    cost (zeroed einsums are not cheaper)."""
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from instaslice_tpu.models.lm import ModelConfig, TpuLM

    cfg = ModelConfig(
        vocab_size=vocab, d_model=d_model, n_heads=4,
        n_layers=n_layers, d_ff=4 * d_model, dtype=jnp.float32,
        remat=False,
    )
    model = TpuLM(cfg)
    params = model.init(jax.random.key(seed))
    blocks = dict(params["blocks"])
    blocks["wo"] = blocks["wo"].at[1:].set(0.0)
    blocks["w_out"] = blocks["w_out"].at[1:].set(0.0)
    params = dict(params, blocks=blocks)
    draft = TpuLM(_dc.replace(cfg, n_layers=1))
    draft_params = {
        "embed": params["embed"],
        "blocks": jax.tree.map(lambda x: x[:1], blocks),
        "ln_f": params["ln_f"],
    }
    return model, params, draft, draft_params


def bench_spec(spec: bool = True, temperature: float = 0.0,
               requests: int = 64, seed: int = 12) -> dict:
    """One spec-tier arm (docs/SERVING.md "Speculative decoding"): the
    bursty mixed-SLO workload over either the speculative hot path
    (draft-propose / target-verify rounds, rejection-sampled at
    temperature > 0, adaptive k, overlapped dispatch) or the plain
    decode baseline serving the IDENTICAL target model. Both arms warm
    their full compiled sets up front and must quiesce with clean
    ledgers and the compiled-program count inside the documented
    budget."""
    from instaslice_tpu.serving import ServingEngine

    model, params, dm, dp = _spec_model_pair()

    def factory(max_batch, max_len, prefill_len, kv_block_size):
        # max_len 512, not the serving tier's 128: decode is HBM-bound
        # on the cache stream, and a serving-realistic cache is where
        # that bound lives — the verify forward streams the cache ONCE
        # per k+1 tokens while plain decode streams it every step, so
        # a toy-short cache would understate exactly the cost
        # speculation removes. Both arms get the identical cache.
        return ServingEngine(
            model, params, max_batch=max_batch, max_len=512,
            prefill_len=prefill_len, kv_block_size=kv_block_size,
            temperature=temperature,
            draft_model=dm if spec else None,
            draft_params=dp if spec else None,
            spec_k=8,
        )

    out = bench_serving(requests=requests, seed=seed,
                        engine_factory=factory, **SPEC_WORKLOAD)
    out["arm"] = "spec" if spec else "no-spec"
    out["temperature"] = temperature
    return out


def smoke_spec(floor: float = None) -> int:
    """``make bench-spec-smoke``: a <60 s run of BOTH arms at
    temperature > 0 (the rejection-sampling path — greedy is its
    special case and the slow tier pins it bit-exactly) — asserts the
    spec arm sustains at least ``TPUSLICE_SPEC_FLOOR`` (default 0.9, a
    REGRESSION floor like the engine/prefix smokes — the recorded
    ``--spec`` tier gates the strict win on both axes) times the
    no-spec baseline's tok/s with real acceptance, zero hung requests,
    ledgers reconciling with zero leaked blocks/locks after quiesce,
    and the compiled-program set inside the documented budget."""
    if floor is None:
        floor = float(os.environ.get("TPUSLICE_SPEC_FLOOR", "0.9"))
    # one rep of a LONGER measured window per arm, not best-of-short:
    # each arm pays ~10 s of engine build + compile warm-up around a
    # ~1 s measurement, so repeats blow the <60 s budget while a 32-
    # request window already averages the OS-noise bursts a short one
    # flips on (the recorded --spec tier keeps best-of-4)
    reqs = int(os.environ.get("TPUSLICE_SPEC_SMOKE_REQS", "32"))
    reps = max(1, int(os.environ.get(
        "TPUSLICE_SPEC_SMOKE_REPEATS", "1")))
    # throwaway process-warming run (see smoke_engine)
    bench_spec(spec=False, temperature=0.7, requests=6)
    bases, opts = [], []
    for _ in range(reps):
        bases.append(bench_spec(spec=False, temperature=0.7,
                                requests=reqs))
        opts.append(bench_spec(spec=True, temperature=0.7,
                               requests=reqs))
    base = max(bases, key=lambda r: r["client_tokens_per_sec"])
    opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
    print(json.dumps({"spec": opt, "no_spec_baseline": base}))
    failures = []
    for arm in (base, opt):
        if arm["hung"]:
            failures.append(f"{arm['arm']}: {arm['hung']} hung")
        if arm["errors"]:
            failures.append(
                f"{arm['arm']}: {arm['errors']} loadgen error(s)"
            )
        if not arm["ledger_ok"]:
            failures.append(
                f"{arm['arm']}: ledger did not reconcile"
            )
        if arm["compiled_over_budget"]:
            failures.append(
                f"{arm['arm']}: compiled programs over budget: "
                f"{arm['compiled_over_budget']}"
            )
    if opt["client_tokens_per_sec"] < floor * base[
            "client_tokens_per_sec"]:
        failures.append(
            f"spec arm {opt['client_tokens_per_sec']} tok/s under "
            f"{floor}x the no-spec baseline "
            f"{base['client_tokens_per_sec']}"
        )
    if opt.get("spec_rounds", 0) <= 0:
        failures.append("spec arm never ran a speculative round "
                        "(knob wiring broken?)")
    if opt.get("spec_accepted", 0) <= 0:
        failures.append("spec arm accepted zero draft tokens "
                        "(draft/verify wiring broken?)")
    for f in failures:
        print(f"bench-spec-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


#: the fleet-router tier's workload (docs/SERVING.md "Fleet router &
#: session migration"): prefill-heavy shared-prefix traffic whose
#: prefix WORKING SET overflows one replica's KV pool but fits the
#: fleet's aggregate — the honest single-box shape of the fleet claim.
#: On this one-core CI box N engines CANNOT multiply raw compute (the
#: arms share one core and one GIL, so the near-linear tok/s multiplier
#: the router delivers on real hardware — where each replica owns its
#: slice's chips — is structurally unmeasurable here); what the fleet
#: DOES multiply on one core is KV capacity: prefix-affine routing
#: partitions the working set so each replica's share stays resident,
#: while the single replica thrashes its radix cache and re-pays the
#: 480-token prefill — REAL compute the fleet skips, visible as the
#: measured hit-rate gap (~75-80% vs ~40%) and the tok/s ratio.
ROUTER_WORKLOAD = dict(
    concurrency=12, prompt_len=16, max_tokens=4, jitter=0.0,
    prefix_pool="24:480",
)
#: per-replica engine shape: pool = 6 slots x 896/16 = 336 blocks =
#: 5376 tokens. The 24 x 480-token prefix pool (11520 tokens) overflows
#: one replica's cache headroom severalfold but partitions to ~8
#: prefixes (3840 tokens) per replica of a 3-fleet — which fit.
ROUTER_ENGINE = dict(max_batch=6, max_len=896, prefill_len=32,
                     kv_block_size=16)


def _router_model(d_model: int = 64):
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.models.lm import ModelConfig, TpuLM

    cfg = ModelConfig(
        vocab_size=128, d_model=d_model, n_heads=4, n_layers=2,
        d_ff=4 * d_model, dtype=jnp.float32, remat=False,
    )
    model = TpuLM(cfg)
    return model, model.init(jax.random.key(0))


def _router_replica(model, params, engine_opts=None):
    """One live replica: fresh engine (fresh radix cache — cache state
    IS the experiment), prefill buckets pre-compiled."""
    from instaslice_tpu.serving import ServingEngine
    from instaslice_tpu.serving.api_server import ApiServer

    opts = dict(ROUTER_ENGINE)
    opts.update(engine_opts or {})
    eng = ServingEngine(model, params, **opts)
    eng.warm_prefill_buckets()
    return ApiServer(eng, block_size=16, request_timeout=180).start()


def _replica_ledger_ok(srv) -> bool:
    """Post-quiesce invariants on one replica: nothing live/parked, no
    orphaned imports, every used pool block the radix tree's, zero
    leaked path locks."""
    eng = srv.scheduler.engine
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and (eng.slots or eng.parked):
        time.sleep(0.02)
    return (
        not eng.slots and not eng.parked
        and not srv.scheduler._imports
        and eng.kv.used_blocks() == eng.radix.pool_blocks()
        and not eng._radix_locks
    )


def _stream_probe(url: str, prompt, max_tokens: int, result: dict):
    """One long streaming completion whose tokens are collected for
    oracle comparison — the churn arm's migrated-session witness."""
    import urllib.request

    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps({"prompt": list(prompt),
                         "max_tokens": max_tokens,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    toks = []
    try:
        with urllib.request.urlopen(req, timeout=180) as resp:
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    result["error"] = "stream ended without [DONE]"
                    return
                buf += chunk
                while b"\n\n" in buf:
                    ev, buf = buf.split(b"\n\n", 1)
                    line = ev.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        result["tokens"] = toks
                        return
                    payload = json.loads(data)
                    if "error" in payload:
                        result["error"] = payload["error"]
                        return
                    for c in payload.get("choices", []):
                        toks.extend(c.get("token_ids") or [])
    except Exception as e:  # slicelint: disable=broad-except
        # the probe must ACCOUNT for any failure; the churn gate reads
        # result["error"] — a silent probe death would pass as hung
        result["error"] = f"{type(e).__name__}: {e}"


def _oracle_chains(model, params, engine_opts, prompts, n):
    """Uninterrupted-run oracles for the migration probes: a FRESH
    engine decodes every probe prompt to ``n`` tokens with no churn,
    no migration, no preemption — the chain a migrated session must
    reproduce byte-for-byte. (Engine-vs-model.apply token identity is
    pinned by the test suite; an unjitted apply loop here would cost
    ~1 s/token on CPU and blow the smoke budget.)"""
    from instaslice_tpu.serving import ServingEngine

    opts = dict(ROUTER_ENGINE)
    opts.update(engine_opts or {})
    eng = ServingEngine(model, params, **opts)
    rids = [eng.add_request(list(p)) for p in prompts]
    # add_request sampled token 1; n-1 more steps completes n
    eng.decode_block(n - 1)
    by_rid = {r.request_id: list(r.generated)
              for r in eng.slots.values()}
    return [by_rid[rid][:n] for rid in rids]


def bench_router(replicas: int = 3, requests: int = 48,
                 seed: int = 13, workload: dict = None,
                 engine_opts: dict = None, d_model: int = 192,
                 record_trace: str = "", replay_trace: str = "",
                 warm_requests: int = 20,
                 migration_probe: bool = False) -> dict:
    """One fleet-tier arm: ``replicas`` engine replicas behind the
    prefix/SLO-aware router (``replicas=1`` = the best-single-replica
    baseline, loadgen pointed DIRECTLY at the server — no router hop,
    the tougher comparison). Both arms run an unmeasured warm burst
    first (compiles + steady-state radix caches — cache tiers are
    judged warm), then the measured window; record/replay a loadgen
    trace so every arm sees the IDENTICAL request stream (the
    record/replay satellite doing its job)."""
    from instaslice_tpu.serving.loadgen import run as loadgen_run
    from instaslice_tpu.serving.router import Router

    workload = dict(workload or ROUTER_WORKLOAD)
    model, params = _router_model(d_model)
    servers = [_router_replica(model, params, engine_opts)
               for _ in range(replicas)]
    router = None
    try:
        if replicas > 1:
            router = Router([s.url for s in servers],
                            poll_interval=0.1).start()
            url = router.url
        else:
            url = servers[0].url
        # unmeasured warm burst: jit compiles + the radix steady state
        # (the fleet arm's warm traffic also seeds the router's shadow
        # prefix index through the poll loop)
        # SAME seed as the measured run: the warm burst must warm the
        # measured run's prefix pool (trace-id reuse is already
        # impossible — loadgen salts ids with a per-run nonce)
        loadgen_run(
            url, requests=warm_requests, vocab=128,
            stream=True, timeout=180, seed=seed,
            **dict(workload, concurrency=6),
        )
        if router is not None:
            router.poll_now()      # adopt the warmed digests NOW
        warm = [s.scheduler.stats() for s in servers]
        t0 = time.monotonic()
        report = loadgen_run(
            url, requests=requests, vocab=128, stream=True,
            timeout=180, seed=seed, record_trace=record_trace,
            replay_trace=replay_trace, **workload,
        )
        wall = time.monotonic() - t0
        probe_block = {}
        if migration_probe and router is not None:
            # one live migration through the running fleet: a long
            # streaming probe, exported off its replica mid-decode,
            # must finish token-identical to the uninterrupted oracle
            import urllib.request

            probe: dict = {}
            pt = threading.Thread(
                target=_stream_probe,
                args=(router.url, [3, 1, 4, 1, 5], 64, probe),
                daemon=True,
            )
            pt.start()
            victim = None
            deadline = time.monotonic() + 10
            while victim is None and time.monotonic() < deadline:
                for s in servers:
                    if s.scheduler.stats()["live_slots"]:
                        victim = s
                        break
                time.sleep(0.01)
            if victim is not None:
                req = urllib.request.Request(
                    victim.url + "/v1/sessions/export", data=b"{}",
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=30) as r:
                    json.loads(r.read())
            pt.join(timeout=120)
            [want] = _oracle_chains(model, params, engine_opts,
                                    [[3, 1, 4, 1, 5]], 64)
            probe_block = {
                "probe_ok": probe.get("tokens") == want
                and "error" not in probe,
                "probe_error": probe.get("error"),
                "probe_migrated":
                    router.stats()["migrations"].get("resumed", 0),
            }
        ledgers = [_replica_ledger_ok(s) for s in servers]
        stats = [s.scheduler.stats() for s in servers]
        hits = sum(s["radix"]["hits"] - w["radix"]["hits"]
                   for s, w in zip(stats, warm))
        misses = sum(s["radix"]["misses"] - w["radix"]["misses"]
                     for s, w in zip(stats, warm))
        saved = sum(
            s["radix"]["tokens_saved"] - w["radix"]["tokens_saved"]
            for s, w in zip(stats, warm)
        )
        out = {
            "arm": f"{replicas}-replica"
                   + ("-router" if router else "-direct"),
            "replicas": replicas,
            "seed": seed,
            "requests": report["requests"],
            "ok": report["ok"],
            "hung": report["outcomes"]["hung"],
            "errors": report["errors"],
            "wall_s": round(wall, 2),
            "client_tokens_per_sec": report["client_tokens_per_sec"],
            "ttft_p50_s": report["ttft_p50"],
            "ttft_p95_s": report["ttft_p95"],
            "client_reused_fraction":
                report["prefix_pool"]["reused_fraction"],
            "prefix_hits": hits,
            "prefix_misses": misses,
            "prefix_tokens_saved": saved,
            "ledger_ok": all(ledgers),
            "trace": report.get("trace", {}),
        }
        out.update(probe_block)
        if router is not None:
            rstats = router.stats()
            out["routed"] = rstats["routed"]
            out["router_requests"] = rstats["requests"]
            out["migrations"] = rstats["migrations"]
        return out
    finally:
        if router is not None:
            router.stop()
        for s in servers:
            s.stop()


def bench_router_churn(replicas: int = 3, requests: int = 32,
                       seed: int = 13, probe_tokens: int = 96,
                       d_model: int = 192, workload: dict = None,
                       engine_opts: dict = None) -> dict:
    """The churn arm: kill (drain-remove, sessions migrating out live)
    and re-add a replica MID-RUN under load. Two long streaming probe
    sessions ride the fleet; the one(s) on the removed replica migrate
    mid-stream and must land token-identical to the uninterrupted
    greedy oracle. Gates: zero hung, zero probe errors, every probe
    oracle-exact, ≥1 live migration resumed (not re-prefilled), and
    clean ledgers on every surviving replica."""
    from instaslice_tpu.serving.loadgen import run as loadgen_run
    from instaslice_tpu.serving.router import Router

    workload = dict(workload or ROUTER_WORKLOAD)
    model, params = _router_model(d_model)
    servers = [_router_replica(model, params, engine_opts)
               for _ in range(replicas)]
    replacement = None
    router = Router([s.url for s in servers],
                    poll_interval=0.1).start()
    try:
        # warm burst (compiles; also gives the probes peers to land on)
        loadgen_run(
            router.url, requests=12, vocab=128,
            stream=True, timeout=180, seed=seed,
            **dict(workload, concurrency=6),
        )
        router.poll_now()
        # the probes: long greedy streams whose full token chains we
        # compare against the uninterrupted-run oracles afterwards
        probes = [{"prompt": [3, 1, 4, 1, 5], "result": {}},
                  {"prompt": [2, 7, 1, 8], "result": {}}]
        threads = []
        for p in probes:
            t = threading.Thread(
                target=_stream_probe,
                args=(router.url, p["prompt"], probe_tokens,
                      p["result"]),
                daemon=True,
            )
            t.start()
            threads.append(t)
        # wait until at least one probe holds a live slot somewhere
        victim = None
        deadline = time.monotonic() + 10
        while victim is None and time.monotonic() < deadline:
            for s in servers:
                if s.scheduler.stats()["live_slots"]:
                    victim = s
                    break
            time.sleep(0.01)
        if victim is None:
            raise RuntimeError("no probe ever went live")
        # background load DURING the churn
        lg: dict = {}

        def load():
            lg.update(loadgen_run(
                router.url, requests=requests,
                vocab=128, stream=True, timeout=180, seed=seed,
                **dict(workload, concurrency=8),
            ))

        lt = threading.Thread(target=load, daemon=True)
        lt.start()
        time.sleep(0.2)     # churn lands mid-run, not at its edge
        removed = router.remove_replica(victim.url)   # drain+migrate
        victim.stop()                                 # actually kill it
        # ...and re-add capacity: a FRESH replica (cold cache) joins
        replacement = _router_replica(model, params, engine_opts)
        router.add_replica(replacement.url)
        lt.join(timeout=180)
        for t in threads:
            t.join(timeout=180)
        survivors = [s for s in servers if s is not victim]
        if replacement is not None:
            survivors.append(replacement)
        probe_results = []
        probes_ok = True
        oracles = _oracle_chains(model, params, engine_opts,
                                 [p["prompt"] for p in probes],
                                 probe_tokens)
        for p, want in zip(probes, oracles):
            got = p["result"].get("tokens")
            ok = got == want
            probes_ok = probes_ok and ok and (
                "error" not in p["result"]
            )
            probe_results.append({
                "prompt": p["prompt"],
                "tokens": len(got or []),
                "oracle_exact": ok,
                "error": p["result"].get("error"),
            })
        rstats = router.stats()
        return {
            "arm": "churn",
            "seed": seed,
            "requests": lg.get("requests", 0),
            "ok": lg.get("ok", 0),
            "hung": lg.get("outcomes", {}).get("hung", 1),
            "errors": lg.get("errors", 0),
            "client_tokens_per_sec": lg.get("client_tokens_per_sec"),
            "removed": removed,
            "replaced": replacement.url,
            "probes": probe_results,
            "probes_ok": probes_ok,
            "migrations": rstats["migrations"],
            "migrated_resumed": rstats["migrations"].get("resumed", 0),
            "migrated_fallback": rstats["migrations"].get(
                "fallback", 0),
            "ledger_ok": all(_replica_ledger_ok(s) for s in survivors),
            "surviving_replicas": len(router.replicas()),
        }
    finally:
        router.stop()
        for s in servers:
            try:
                s.stop()
            except Exception:  # slicelint: disable=broad-except
                pass           # the victim is already stopped
        if replacement is not None:
            replacement.stop()


def smoke_router(floor: float = None) -> int:
    """``make bench-router-smoke``: a <60 s 2-replica fleet run gating
    the fast tier — asserts aggregate tok/s ≥ ``TPUSLICE_ROUTER_FLOOR``
    (default 0.5 — a MELTDOWN floor only: all arms time-share this
    box's single core and one GIL, 2-replica fleets hit process-wide
    GIL convoys that halve short windows arm-wide, and the single
    arm's working-set overflow is structurally ≤ 2x at 2 replicas —
    so the REGRESSION burden rides the deterministic gates instead:
    prefix-affine routing actually firing (a broken shadow index reads
    ~0-3 prefix routes), one live migration completing
    token-identically, zero hung requests, and ledgers reconciling on
    both replicas; the recorded ``--router`` tier gates the strict
    capacity win at 3 replicas) × the single-replica baseline on the
    IDENTICAL (recorded→replayed) request stream."""
    import tempfile

    if floor is None:
        floor = float(os.environ.get("TPUSLICE_ROUTER_FLOOR", "0.5"))
    reqs = int(os.environ.get("TPUSLICE_ROUTER_SMOKE_REQS", "24"))
    # shrunken shapes: the same overflow-one-fit-two capacity story at
    # smoke scale (2-replica fleet: per-replica ~10 x 320 = 200 blocks
    # of a 252-block pool; one replica: 20 x 320 overflows ~2x)
    workload = dict(ROUTER_WORKLOAD, prefix_pool="20:320")
    engine = dict(ROUTER_ENGINE, max_len=672)
    dm = int(os.environ.get("TPUSLICE_ROUTER_SMOKE_DMODEL", "128"))
    # throwaway process-warming run (see smoke_engine): thread pools,
    # sockets, allocator — the first serving run in a process is slow
    # for reasons neither arm owns, and the fleet arm runs first
    bench_router(replicas=1, requests=6, workload=workload,
                 engine_opts=engine, warm_requests=4, d_model=dm)
    reps = max(1, int(os.environ.get(
        "TPUSLICE_ROUTER_SMOKE_REPEATS", "2")))
    fleets, singles = [], []
    with tempfile.NamedTemporaryFile(suffix=".jsonl") as f:
        # the one live migration rides the first fleet rep
        # (migration_probe) — the full kill/re-add churn arm is the
        # recorded tier's, a smoke must fit the <60 s budget. Best-of
        # per arm, interleaved: single ~5 s windows on the shared-core
        # CI box swing ±40% on OS noise alone (engine-smoke precedent)
        fleets.append(bench_router(
            replicas=2, requests=reqs, workload=workload,
            engine_opts=engine, record_trace=f.name,
            warm_requests=12, d_model=dm, migration_probe=True))
        singles.append(bench_router(
            replicas=1, requests=reqs, workload=workload,
            engine_opts=engine, replay_trace=f.name,
            warm_requests=12, d_model=dm))
        for _ in range(reps - 1):
            fleets.append(bench_router(
                replicas=2, requests=reqs, workload=workload,
                engine_opts=engine, replay_trace=f.name,
                warm_requests=12, d_model=dm))
            singles.append(bench_router(
                replicas=1, requests=reqs, workload=workload,
                engine_opts=engine, replay_trace=f.name,
                warm_requests=12, d_model=dm))
    probe_rep = fleets[0]
    fleet = max(fleets, key=lambda r: r["client_tokens_per_sec"])
    single = max(singles, key=lambda r: r["client_tokens_per_sec"])
    print(json.dumps({"fleet": fleet, "single": single,
                      "probe_rep": probe_rep,
                      "tokens_per_sec_runs": {
                          "fleet": [r["client_tokens_per_sec"]
                                    for r in fleets],
                          "single": [r["client_tokens_per_sec"]
                                     for r in singles],
                      }}))
    failures = []
    for arm in (fleet, single, probe_rep):
        if arm["hung"]:
            failures.append(f"{arm['arm']}: {arm['hung']} hung")
        if arm["errors"]:
            failures.append(
                f"{arm['arm']}: {arm['errors']} loadgen error(s)")
        if not arm["ledger_ok"]:
            failures.append(f"{arm['arm']}: ledger did not reconcile")
    if fleet["client_tokens_per_sec"] < floor * single[
            "client_tokens_per_sec"]:
        failures.append(
            f"fleet {fleet['client_tokens_per_sec']} tok/s under "
            f"{floor}x the single replica "
            f"{single['client_tokens_per_sec']}"
        )
    # the DETERMINISTIC wiring gate: prefix-affine routing must
    # actually fire (the broken-shadow-index failure mode measured
    # ~0-3 prefix routes and still cleared a pure tok/s floor)
    if fleet.get("routed", {}).get("prefix", 0) < 5:
        failures.append(
            "prefix-affine routing barely fired "
            f"({fleet.get('routed')}) — shadow index broken?"
        )
    if not probe_rep.get("probe_ok"):
        failures.append(
            "migration probe not token-identical: "
            f"{probe_rep.get('probe_error')}"
        )
    if probe_rep.get("probe_migrated", 0) < 1:
        failures.append("no session completed a live migration "
                        "(resume path never ran)")
    for f in failures:
        print(f"bench-router-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


def _run_tpu_phase(phase: str, timeout: float, env: dict,
                   pass_fds=()) -> dict:
    """One phase in its own subprocess; returns its JSON fragment or a
    ``{"error": ...}`` fragment for timeouts / crashes / no-JSON.

    Timeout is enforced SIGINT-first: hard-killing a TPU claimant leaves
    a stale remote claim that wedges the tunnel for hours
    (``docs/PERF.md``), so a stuck phase first gets a KeyboardInterrupt
    and a grace window to unwind its backend before SIGKILL.

    ``pass_fds`` carries the watchdog's locked flock fd down to the
    child (with ``TPUSLICE_TPU_LOCK_FD`` in ``env``) so the whole
    probe→phases burst runs under ONE held claim."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "instaslice_tpu.bench_tpu",
         "--phase", phase],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        cwd=_HERE,
        env=env,
        pass_fds=pass_fds,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        proc = subprocess.CompletedProcess(
            proc.args, proc.returncode, stdout, stderr
        )
    except subprocess.TimeoutExpired:
        how = "SIGINT"
        proc.send_signal(signal.SIGINT)
        try:
            proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            how = "SIGKILL (ignored SIGINT for 20s)"
            proc.kill()
            proc.communicate()
        return {"error": (
            f"phase exceeded its {timeout:.0f}s cap, stopped via {how} "
            "(chip unreachable, tunnel hung, or compile too slow)"
        ), "timed_out": True}
    out: dict = {}
    parsed = False
    lines = (proc.stdout or b"").decode().strip().splitlines()
    for line in reversed(lines):  # last JSON line wins; skip stray prints
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict):  # bare scalars ('0', 'null') also parse
            out = cand
            parsed = True
            break
    if not parsed:
        out["error"] = (
            f"phase emitted no JSON (rc={proc.returncode}): "
            + (proc.stderr or proc.stdout or b"").decode()[-300:]
        )
    elif proc.returncode != 0 and "error" not in out:
        out["error"] = (
            (proc.stderr or b"").decode()[-300:].strip()
            or f"phase exited rc={proc.returncode} with no stderr"
        )
    return out


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


#: stored phases older than this are dropped at load: the store file is
#: committed, so without an age gate the NEXT round's bench would fold
#: last round's numbers while claiming they were "captured earlier in
#: the round" — and its watchdog would see nothing missing and exit.
#: A round is ~12 h; 14 h keeps everything from this round only.
STORE_MAX_AGE_H = float(os.environ.get("TPUSLICE_BENCH_STORE_MAX_AGE_H",
                                       "14"))


def _load_store() -> dict:
    try:
        with open(RESULTS_STORE) as f:
            store = json.load(f)
        if not (isinstance(store, dict)
                and isinstance(store.get("phases"), dict)):
            raise ValueError("not a store")
    except (OSError, ValueError):
        return {"phases": {}, "phase_ts": {}}
    cutoff = (datetime.datetime.now(datetime.timezone.utc)
              - datetime.timedelta(hours=STORE_MAX_AGE_H))
    fresh: dict = {"phases": {}, "phase_ts": {}}
    for phase, frag in store["phases"].items():
        ts = store.get("phase_ts", {}).get(phase, "")
        try:
            when = datetime.datetime.strptime(
                ts, "%Y-%m-%dT%H:%M:%SZ"
            ).replace(tzinfo=datetime.timezone.utc)
        except (TypeError, ValueError):
            continue      # unstamped/mistyped = untrusted: drop
        if when >= cutoff:
            fresh["phases"][phase] = frag
            fresh["phase_ts"][phase] = ts
    return fresh


def _save_store(store: dict) -> None:
    """Atomic write: a wedge (or SIGKILL) mid-save must not destroy the
    phases already captured. The tmp name is per-pid — two writers
    sharing one tmp path would rename each other's file away
    mid-write."""
    store["updated"] = _utcnow()
    tmp = f"{RESULTS_STORE}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(store, f, indent=1)
        f.write("\n")
    os.replace(tmp, RESULTS_STORE)


@contextlib.contextmanager
def _store_lock():
    """Serialize store read-modify-write across processes: a sidecar
    flock (released with the fd even on SIGKILL; the file is never
    unlinked — removing it would let a third writer lock a different
    inode under the same path)."""
    fd = os.open(RESULTS_STORE + ".lock", os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)


def _journal_probe(frag: dict, source: str):
    """Journal one probe result in the canonical shape; returns the
    probe's error (None = alive) so callers can branch on it."""
    err = frag.get("error")
    _journal({
        "alive": err is None,
        "rtt_ms": frag.get("readback_rtt_ms"),
        **({"error": err[:200]} if err else {}),
        "source": source,
    })
    return err


def _journal(event: dict) -> None:
    """Append one line to the chip-health journal, flushed immediately."""
    event = {"ts": _utcnow(), **event}
    with open(HEALTH_JOURNAL, "a") as f:
        f.write(json.dumps(event) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _tpu_env() -> dict:
    env = dict(os.environ)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(_HERE, ".jax_cache")
    )
    return env


def _record_phase(phase: str, frag: dict) -> dict:
    """Persist one phase fragment with a fresh-load merge: the store is
    re-read immediately before the write so a fragment another process
    persisted since our last load is never clobbered by a whole-file
    rewrite. (Capture bursts hold the host flock, so two writers cannot
    actually burst concurrently — this guards the load-before-lock and
    crash-recovery windows.)"""
    with _store_lock():
        store = _load_store()
        store["phases"][phase] = frag
        store["phase_ts"][phase] = _utcnow()
        _save_store(store)
    return store


def bench_tpu() -> dict:
    """Run each on-chip phase in its own subprocess under its own cap and
    a shared total budget. Fragments merge incrementally; per-phase
    failures land as ``tpu_<phase>_error`` keys so one hung phase cannot
    forfeit the others' numbers (the round-2 failure mode). Every
    successful fragment is ALSO persisted to :data:`RESULTS_STORE` the
    moment it lands, and when the probe finds the chip dead, numbers a
    watchdog (or an earlier run) already captured this round are folded
    in from the store — with ``tpu_results_provenance`` naming their
    capture times — instead of reporting nothing for the fourth round
    running."""
    from instaslice_tpu.utils.tpulock import (
        INHERITED_FD_ENV, TpuBusyError, TpuClaim, tpu_is_cpu_forced,
    )

    env = _tpu_env()
    out: dict = {}
    claim = None
    pass_fds = ()
    if not tpu_is_cpu_forced():
        # hold the host flock for the WHOLE bench, handing the fd to
        # each phase child: a looping watchdog can then never slip in
        # between two phases and burn the bench's budget on lock-busy
        # errors. If something else (a watchdog mid-burst) holds it,
        # wait it out — its burst fills the same store we fold from.
        try:
            claim = TpuClaim().acquire(timeout=300)
            env[INHERITED_FD_ENV] = str(claim.fd)
            pass_fds = (claim.fd,)
        except TpuBusyError as e:
            out["tpu_error"] = f"TPU lock busy for 300s: {e}"
            for phase, _ in TPU_PHASES:
                out[f"tpu_{phase}_error"] = "skipped: TPU lock busy"
            _fold_store(out, _load_store())
            return out
    try:
        deadline = time.monotonic() + TPU_BENCH_TIMEOUT
        for phase, cap in TPU_PHASES:
            remaining = deadline - time.monotonic()
            if remaining < 15:
                out[f"tpu_{phase}_error"] = (
                    f"skipped: total bench budget "
                    f"({TPU_BENCH_TIMEOUT:.0f}s) exhausted by earlier "
                    "phases"
                )
                continue
            frag = _run_tpu_phase(phase, min(cap, remaining), env,
                                  pass_fds=pass_fds)
            err = frag.pop("error", None)
            frag.pop("timed_out", None)
            out.update(frag)
            if phase == "probe":
                _journal({
                    "alive": err is None,
                    "rtt_ms": frag.get("readback_rtt_ms"),
                    **({"error": err[:200]} if err else {}),
                    "source": "bench",
                })
            if err is not None:
                err = err or "phase failed with empty error message"
                out[f"tpu_{phase}_error"] = err
                print(f"[bench] {phase}: ERROR {err}", file=sys.stderr)
                if phase == "probe":
                    # the probe exists so a dead/missing chip fails
                    # CHEAPLY; grinding the expensive phases against it
                    # would drain the budget into guaranteed timeouts
                    out["tpu_error"] = err
                    for rest, _ in TPU_PHASES:
                        if rest != "probe" \
                                and f"tpu_{rest}_error" not in out:
                            out[f"tpu_{rest}_error"] = (
                                "skipped: probe failed (chip dead or "
                                "unreachable)"
                            )
                    break
            else:
                print(f"[bench] {phase}: {json.dumps(frag)}",
                      file=sys.stderr)
                if frag:
                    _record_phase(phase, frag)
    finally:
        if claim is not None:
            claim.release()
    _fold_store(out, _load_store())
    return out


def _fold_store(out: dict, store: dict) -> None:
    """Fill any phase this run did NOT measure live (its
    ``tpu_<phase>_error`` key is set — probe dead, lock busy, budget
    exhausted, or a phase-specific failure) from the store, when the
    chip answered earlier in the round: ship what was actually
    captured, with provenance naming each phase's capture time. Phases
    measured live this run have no error key and are never touched."""
    recovered = []
    for phase, frag in store["phases"].items():
        if f"tpu_{phase}_error" not in out:
            continue              # measured live this run: keep that
        out.update(frag)
        out.pop(f"tpu_{phase}_error", None)
        recovered.append(f"{phase}@{store['phase_ts'].get(phase, '?')}")
    if recovered:
        out["tpu_results_provenance"] = (
            "phases not measurable at bench time were filled from "
            "captures made live earlier in the round (watchdog or a "
            "previous run — see TPU_HEALTH.jsonl for the chip-health "
            "timeline): " + ", ".join(sorted(recovered))
        )


#: watchdog phase priority — what a SHORT recovery window should record
#: first: proof-of-life + RTT, the kernel headline, the 7B serving
#: headline, the training headline, then the rest.
WATCHDOG_PRIORITY = [
    "probe", "flash_fwd", "serving_7b", "mfu", "flash_bwd", "serving",
    "serving_quant", "serving_lora", "serving_spec",
    "serving_small", "serving_tp", "moe",
    # moe last: its two fresh model compiles make it the slowest phase
    # by far (three 480s timeouts on 2026-07-31), and a slow phase
    # early in the order delays everything behind it
]
_PHASE_CAPS = dict(TPU_PHASES)


def watchdog(interval: float, max_hours: float, once: bool) -> int:
    """Wait out a wedged tunnel cheaply; capture greedily on recovery.

    Loop: take the host-wide flock, fire the short-cap probe subprocess
    (co-holding the claim via the inherited locked fd), journal
    ``{ts, alive, rtt_ms}``; when the chip answers, run the remaining
    phases in :data:`WATCHDOG_PRIORITY` order, persisting each fragment
    to :data:`RESULTS_STORE` as it lands — a wedge mid-burst keeps
    everything already measured, and the next recovery window resumes
    with the phases still missing. The flock is held only for the
    burst, then released for the sleep, so a driver-launched
    ``python bench.py`` never finds the chip "busy" because of a
    sleeping watchdog. Exits 0 once every phase has a stored fragment
    (or after one cycle with ``once``); exits 3 when ``max_hours``
    elapse with phases still missing."""
    from instaslice_tpu.utils.tpulock import (
        INHERITED_FD_ENV, TpuBusyError, TpuClaim,
    )

    env = _tpu_env()
    deadline = time.monotonic() + max_hours * 3600

    def _missing() -> list:
        phases = _load_store()["phases"]
        return [p for p in WATCHDOG_PRIORITY
                if p != "probe" and p not in phases]

    while True:
        if not _missing():
            print("[watchdog] all phases captured; exiting",
                  file=sys.stderr)
            return 0
        claim = None
        try:
            try:
                claim = TpuClaim().acquire(timeout=10)
            except TpuBusyError as e:
                # a real claimant (e.g. the driver's bench) is on the
                # chip — that is itself proof of life worth journaling
                _journal({"alive": None, "source": "watchdog",
                          "error": f"lock busy: {e}"})
                raise
            env[INHERITED_FD_ENV] = str(claim.fd)
            frag = _run_tpu_phase("probe", _PHASE_CAPS["probe"], env,
                                  pass_fds=(claim.fd,))
            err = frag.get("error")
            _journal_probe(frag, "watchdog")
            if err is None:
                _record_phase("probe", {
                    k: v for k, v in frag.items()
                    if k not in ("error", "timed_out")
                })
                # re-list under the held lock: another capture path may
                # have landed phases since the top-of-loop check
                missing = _missing()
                print(f"[watchdog] chip ALIVE "
                      f"(rtt {frag.get('readback_rtt_ms')} ms); "
                      f"capturing {len(missing)} missing phases",
                      file=sys.stderr)
                for phase in missing:
                    frag = _run_tpu_phase(
                        phase, _PHASE_CAPS[phase], env,
                        pass_fds=(claim.fd,),
                    )
                    err = frag.pop("error", None)
                    if err is not None:
                        _journal({"phase": phase, "error": err[:200],
                                  "source": "watchdog"})
                        print(f"[watchdog] {phase}: ERROR {err}",
                              file=sys.stderr)
                        if frag.get("timed_out"):
                            # a chronically slow phase and a wedged
                            # tunnel look identical from out here —
                            # distinguish with a cheap re-probe, or a
                            # slow phase early in the priority order
                            # starves every phase behind it (moe did
                            # exactly this on 2026-07-31: three bursts
                            # died at moe with the tail never tried)
                            p2 = _run_tpu_phase(
                                "probe", _PHASE_CAPS["probe"], env,
                                pass_fds=(claim.fd,),
                            )
                            # every probe is journaled — the health
                            # timeline must cover exactly the moments
                            # around timeouts one diagnoses with it
                            p2err = _journal_probe(p2, "watchdog")
                            if p2err is not None:
                                break  # probe dead too: real wedge
                            print(f"[watchdog] chip still alive after "
                                  f"{phase} timeout; continuing burst",
                                  file=sys.stderr)
                        continue      # phase-specific failure: next one
                    _record_phase(phase, frag)
                    _journal({"phase": phase, "captured": True,
                              "source": "watchdog"})
                    print(f"[watchdog] {phase}: {json.dumps(frag)}",
                          file=sys.stderr)
        except TpuBusyError:
            pass
        finally:
            if claim is not None:
                env.pop(INHERITED_FD_ENV, None)
                claim.release()
        if not _missing():
            # completion beats the deadline/sleep: a burst that just
            # captured the last phase must exit 0 NOW, not sleep an
            # interval (or worse, hit the deadline and report failure)
            print("[watchdog] all phases captured; exiting",
                  file=sys.stderr)
            return 0
        if once:
            return 0
        if time.monotonic() >= deadline:
            print("[watchdog] max-hours elapsed; exiting", file=sys.stderr)
            return 3
        time.sleep(interval)


def smoke(floor: float = 5.0) -> int:
    """``make bench-smoke``: a <60 s shrunken scale run gating the fast
    tier — asserts a grants/sec floor and ZERO reconcile errors on a
    sharded-worker fleet sim. Catches control-plane throughput
    regressions (and any worker-concurrency crash) in CI, not at the
    next 1k-node bench."""
    t0 = time.monotonic()
    out = bench_scale(
        n_nodes=int(os.environ.get("TPUSLICE_SMOKE_NODES", "60")),
        n_pods=int(os.environ.get("TPUSLICE_SMOKE_PODS", "120")),
        timeout=50.0,
    )
    out["smoke_wall_s"] = round(time.monotonic() - t0, 1)
    print(json.dumps(out))
    failures = []
    if out.get("error"):
        failures.append(out["error"])
    if out["grants_per_sec"] < floor:
        failures.append(
            f"grants/sec {out['grants_per_sec']} below floor {floor}"
        )
    if out["reconcile_errors"]:
        failures.append(
            f"{out['reconcile_errors']} reconcile error(s) — every "
            "grant must reconcile clean"
        )
    for f in failures:
        print(f"bench-smoke FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench.py",
        description="control-plane + on-chip bench; --watchdog waits out "
        "a wedged TPU tunnel and captures phases on recovery; --scale "
        "runs the fleet-scale grants/sec tier; --smoke is the <60s CI "
        "gate over a shrunken scale run",
    )
    ap.add_argument("--watchdog", action="store_true",
                    help="run the chip-health watchdog loop instead of "
                    "the one-shot bench")
    ap.add_argument("--scale", action="store_true",
                    help="fleet-scale control-plane bench (grants/sec + "
                    "gate-to-ungate p95/p99 on the 1k-node sim)")
    ap.add_argument("--scale-baseline", action="store_true",
                    help="with --scale: also measure the serial re-list "
                    "baseline control plane and report the ratio")
    ap.add_argument("--nodes", type=int, default=1000,
                    help="scale tier: simulated node count")
    ap.add_argument("--pods", type=int, default=2000,
                    help="scale tier: pending pod burst size")
    ap.add_argument("--baseline-pods", type=int, default=200,
                    help="scale tier: burst size for the (much slower) "
                    "baseline measurement")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: shrunken scale run asserting a "
                    "grants/sec floor and zero reconcile errors")
    ap.add_argument("--smoke-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_SMOKE_FLOOR", "5.0")),
                    help="bench-smoke grants/sec floor")
    ap.add_argument("--defrag", action="store_true",
                    help="defragmentation tier: seeded churny "
                    "multi-profile sim, frag-aware + repacker vs "
                    "first-fit-no-repack (capacity utilization + "
                    "NoCapacity-wait p95), plus a chaos arm injecting "
                    "a realize failure mid-migration")
    ap.add_argument("--defrag-smoke", action="store_true",
                    help="CI gate: <60 s single-group churn run "
                    "asserting utilization recovery, every blocked pod "
                    "granted, and strictly legal transition chains")
    ap.add_argument("--defrag-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_DEFRAG_FLOOR", "0.5")),
                    help="bench-defrag-smoke utilization floor")
    ap.add_argument("--defrag-seed", type=int, default=7,
                    help="defrag tier: churn workload seed")
    ap.add_argument("--serving", action="store_true",
                    help="serving-scheduler tier: mixed-SLO multi-"
                    "tenant loadgen at mixed sequence lengths, "
                    "continuous-batching scheduler vs the fixed-"
                    "decode-round baseline (tok/s, per-class TTFT, "
                    "SLO attainment, paged-vs-legacy kv utilization)")
    ap.add_argument("--serving-smoke", action="store_true",
                    help="CI gate: <60 s mixed-SLO serving run "
                    "asserting latency-class SLO attainment and a kv-"
                    "utilization floor (TPUSLICE_SERVING_SLO_FLOOR / "
                    "TPUSLICE_SERVING_KV_FLOOR)")
    ap.add_argument("--serving-slo-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_SERVING_SLO_FLOOR", "0.75")),
                    help="serving-smoke: latency-class SLO attainment "
                    "floor")
    ap.add_argument("--serving-kv-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_SERVING_KV_FLOOR", "0.5")),
                    help="serving-smoke: mean paged kv-utilization "
                    "floor under load")
    ap.add_argument("--serving-seed", type=int,
                    default=int(os.environ.get(
                        "TPUSLICE_SERVING_SEED", "9")),
                    help="serving tier: loadgen scenario seed")
    ap.add_argument("--engine", action="store_true",
                    help="engine hot-path tier: bursty-admission "
                    "mixed-SLO workload, batched-prefill + overlap "
                    "arm vs the per-slot PR 9 baseline (tok/s, TTFT "
                    "p95, prefill-batch occupancy)")
    ap.add_argument("--engine-smoke", action="store_true",
                    help="CI gate: <60 s run of both engine arms "
                    "asserting hot-path tok/s >= TPUSLICE_ENGINE_FLOOR"
                    " x the per-slot baseline, zero hung requests, "
                    "and a reconciling preempt/resume ledger")
    ap.add_argument("--engine-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_ENGINE_FLOOR", "0.9")),
                    help="engine-smoke: hot-path tok/s floor as a "
                    "multiple of the per-slot baseline (0.9 absorbs "
                    "shared-core CI noise; the full --engine tier "
                    "gates a strict win)")
    ap.add_argument("--engine-seed", type=int,
                    default=int(os.environ.get(
                        "TPUSLICE_ENGINE_SEED", "10")),
                    help="engine tier: loadgen scenario seed")
    ap.add_argument("--prefix", action="store_true",
                    help="radix prefix-cache tier: seeded shared-"
                    "prefix loadgen workload, radix arm vs the "
                    "exact-match-only baseline (tok/s, TTFT p95, "
                    "prefix-hit token savings)")
    ap.add_argument("--prefix-smoke", action="store_true",
                    help="CI gate: <60 s shared-prefix run of both "
                    "arms asserting radix tok/s >= "
                    "TPUSLICE_PREFIX_FLOOR (0.9, a regression "
                    "floor) x the exact-match "
                    "baseline, prefix-hit savings > 0, reconciling "
                    "ledgers and zero leaked blocks")
    ap.add_argument("--prefix-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_PREFIX_FLOOR", "0.9")),
                    help="prefix-smoke: radix tok/s floor as a "
                    "multiple of the exact-match baseline")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding tier: spec arm vs the "
                         "no-spec baseline on the bursty mixed-SLO "
                         "workload at temperature 0 AND >0, best-of-4 "
                         "interleaved per arm (tok/s AND TTFT p95 must "
                         "both win at both temperatures) — records "
                         "BENCH_SPEC_r12.json")
    ap.add_argument("--spec-smoke", action="store_true",
                    help="<60 s spec regression gate for make test "
                         "(TPUSLICE_SPEC_FLOOR x no-spec tok/s, "
                         "ledgers, compile budget)")
    ap.add_argument("--spec-floor", type=float,
                    default=float(os.environ.get(
                        "TPUSLICE_SPEC_FLOOR", "0.9")),
                    help="spec-smoke: spec tok/s floor as a fraction "
                         "of the no-spec baseline")
    ap.add_argument("--spec-seed", type=int,
                    default=int(os.environ.get(
                        "TPUSLICE_SPEC_SEED", "12")),
                    help="spec tier loadgen seed")
    ap.add_argument("--prefix-seed", type=int,
                    default=int(os.environ.get(
                        "TPUSLICE_PREFIX_SEED", "11")),
                    help="prefix tier: loadgen scenario seed")
    ap.add_argument("--router", action="store_true",
                    help="full fleet-router tier: loadgen at a "
                         "3-replica router vs the best single replica "
                         "on the identical recorded→replayed stream "
                         "(fleet must win tok/s by "
                         "TPUSLICE_ROUTER_RECORD_FLOOR with TTFT p95 "
                         "no worse — the single-core CI box measures "
                         "the prefix-capacity mechanism, not the "
                         "hardware replica multiplier) plus the churn "
                         "arm (replica kill + re-add mid-run, "
                         "migrated sessions oracle-exact, ledgers "
                         "clean) — records BENCH_ROUTER_r13.json")
    ap.add_argument("--router-smoke", action="store_true",
                    help="<60 s 2-replica fleet gate for make test "
                         "(aggregate >= TPUSLICE_ROUTER_FLOOR x "
                         "single, one live migration token-identical, "
                         "zero hung, ledgers reconcile)")
    ap.add_argument("--router-seed", type=int,
                    default=int(os.environ.get(
                        "TPUSLICE_ROUTER_SEED", "13")),
                    help="router tier: loadgen scenario seed")
    ap.add_argument("--interval", type=float, default=900.0,
                    help="watchdog: seconds between probes (default 900)")
    ap.add_argument("--max-hours", type=float, default=11.0,
                    help="watchdog: give up after this long")
    ap.add_argument("--once", action="store_true",
                    help="watchdog: one probe cycle, then exit")
    ap.add_argument("--drop-phases", default="",
                    help="comma-separated phase names to remove from the "
                    "results store so the next watchdog cycle (or bench "
                    "run) re-captures them — e.g. after a code change "
                    "that invalidates their numbers")
    args = ap.parse_args(argv)
    if args.drop_phases:
        names = [n.strip() for n in args.drop_phases.split(",") if n.strip()]
        unknown = [n for n in names if n not in _PHASE_CAPS]
        if unknown:
            print(f"unknown phases: {unknown}; valid: "
                  f"{list(_PHASE_CAPS)}", file=sys.stderr)
            return 2
        with _store_lock():
            store = _load_store()
            dropped = [n for n in names if store["phases"].pop(n, None)
                       is not None]
            for n in dropped:
                store["phase_ts"].pop(n, None)
            _save_store(store)
        print(f"dropped {dropped}; store now holds "
              f"{sorted(store['phases'])}")
        return 0
    if args.watchdog:
        return watchdog(args.interval, args.max_hours, args.once)
    if args.smoke:
        return smoke(floor=args.smoke_floor)
    if args.defrag_smoke:
        return smoke_defrag(floor=args.defrag_floor)
    if args.serving_smoke:
        return smoke_serving(slo_floor=args.serving_slo_floor,
                             kv_floor=args.serving_kv_floor)
    if args.engine_smoke:
        return smoke_engine(floor=args.engine_floor)
    if args.prefix_smoke:
        return smoke_prefix(floor=args.prefix_floor)
    if args.spec_smoke:
        return smoke_spec(floor=args.spec_floor)
    if args.router_smoke:
        return smoke_router()
    if args.router:
        import tempfile

        result = {
            "metric": "router_tokens_per_sec",
            "unit": "tokens/s",
        }
        # best-of-N per arm on the IDENTICAL request stream: the first
        # fleet run RECORDS the loadgen trace (closed-loop arrivals at
        # the fleet's own pace), every later run — fleet and single —
        # REPLAYS it, so the comparison is one stream against two
        # topologies, not two draws from one distribution. The single
        # replica gets the same offered arrival times; what it cannot
        # absorb it queues, which is exactly what "adding a slice adds
        # zero capacity" looks like from the client.
        reps = max(1, int(os.environ.get(
            "TPUSLICE_ROUTER_REPEATS", "2")))
        # throwaway process-warming run (see smoke_engine)
        bench_router(replicas=1, requests=6, warm_requests=4,
                     seed=args.router_seed)
        fleets, singles = [], []
        with tempfile.NamedTemporaryFile(suffix=".jsonl") as f:
            fleets.append(bench_router(
                replicas=3, seed=args.router_seed,
                record_trace=f.name,
            ))
            for _ in range(reps - 1):
                fleets.append(bench_router(
                    replicas=3, seed=args.router_seed,
                    replay_trace=f.name,
                ))
            for _ in range(reps):
                singles.append(bench_router(
                    replicas=1, seed=args.router_seed,
                    replay_trace=f.name,
                ))
        fleet = max(fleets, key=lambda r: r["client_tokens_per_sec"])
        single = max(singles,
                     key=lambda r: r["client_tokens_per_sec"])
        churn = bench_router_churn(replicas=3, seed=args.router_seed)
        result["router_fleet"] = fleet
        result["single_replica_baseline"] = single
        result["churn"] = churn
        result["repeats"] = reps
        result["tokens_per_sec_runs"] = {
            "fleet": [r["client_tokens_per_sec"] for r in fleets],
            "single": [r["client_tokens_per_sec"] for r in singles],
        }
        result["value"] = fleet["client_tokens_per_sec"]
        if single["client_tokens_per_sec"]:
            result["vs_baseline"] = round(
                fleet["client_tokens_per_sec"]
                / single["client_tokens_per_sec"], 2
            )
        # headline keys in the shared BENCH_*.json shape (the perf
        # trajectory tracker scans recorded files for these)
        result["serve_toks_per_sec"] = fleet["client_tokens_per_sec"]
        result["serve_ttft_p95"] = fleet["ttft_p95_s"]
        result["ttft_p95_baseline_s"] = single["ttft_p95_s"]
        # the fleet's hardware claim is near-linear tok/s with
        # replica count (>= 2.5x at 3 replicas) — structurally
        # unmeasurable on this box, where every replica time-shares
        # ONE core and one GIL with the client and the router (see
        # ROUTER_WORKLOAD). What IS measurable here is the capacity
        # mechanism itself: the recorded floor gates the prefix-
        # working-set win (aggregate KV + prefix-affine routing saving
        # the single replica's re-prefill compute), and the recorded
        # JSON carries the hit-rate gap that explains it.
        record_floor = float(os.environ.get(
            "TPUSLICE_ROUTER_RECORD_FLOOR", "1.25"))
        result["record_floor"] = record_floor
        result["single_core_note"] = (
            "all replicas time-share one CPU core + one GIL; the "
            "fleet's tok/s edge here is the prefix-capacity "
            "mechanism only — on hardware where each replica owns "
            "its slice, the compute multiplier stacks on top"
        )
        print(json.dumps(result))
        ok = (
            fleet["hung"] == 0 and single["hung"] == 0
            and churn["hung"] == 0
            and fleet["errors"] == 0 and single["errors"] == 0
            and churn["errors"] == 0
            and fleet["ledger_ok"] and single["ledger_ok"]
            and churn["ledger_ok"]
            # the recorded gate: the fleet must beat the single
            # replica by the documented floor with TTFT p95 no worse
            # (1.1x tolerance: best-of-rep selection is by tok/s, and
            # p95 of a ~50-request window moves ~10% on one stray OS
            # preemption)
            and fleet["client_tokens_per_sec"]
            >= record_floor * single["client_tokens_per_sec"]
            and fleet["ttft_p95_s"] <= 1.1 * single["ttft_p95_s"]
            # the fleet must actually be USING its cache edge, not
            # winning on noise: strictly more prefix hits than the
            # thrashing single replica
            and fleet["prefix_hits"] > single["prefix_hits"]
            # churn: sessions MIGRATED (resume path), oracle-exact
            and churn["probes_ok"]
            and churn["migrated_resumed"] >= 1
        )
        return 0 if ok else 1
    if args.spec:
        result = {
            "metric": "spec_tokens_per_sec",
            "unit": "tokens/s",
        }
        # best-of-N per arm, interleaved, at BOTH temperatures: the
        # lossless claim is only worth shipping if the sampled path
        # wins too, and the prefix-tier precedent (4 reps, ceilings
        # compared) holds on the nproc=1 CI box
        reps = max(1, int(os.environ.get(
            "TPUSLICE_SPEC_REPEATS", "4")))
        # throwaway process-warming run (see smoke_engine)
        bench_spec(spec=False, temperature=0.0, requests=6,
                   seed=args.spec_seed)
        ok = True
        for label, temp in (("greedy", 0.0), ("sampled", 0.7)):
            opts, bases = [], []
            for _ in range(reps):
                opts.append(bench_spec(spec=True, temperature=temp,
                                       seed=args.spec_seed))
                bases.append(bench_spec(spec=False, temperature=temp,
                                        seed=args.spec_seed))
            opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
            base = max(bases, key=lambda r: r["client_tokens_per_sec"])
            result[f"spec_{label}"] = opt
            result[f"nospec_{label}_baseline"] = base
            result[f"tokens_per_sec_runs_{label}"] = {
                "spec": [r["client_tokens_per_sec"] for r in opts],
                "no_spec": [r["client_tokens_per_sec"] for r in bases],
            }
            if base["client_tokens_per_sec"]:
                result[f"vs_baseline_{label}"] = round(
                    opt["client_tokens_per_sec"]
                    / base["client_tokens_per_sec"], 2
                )
            ok = ok and (
                opt["hung"] == 0 and base["hung"] == 0
                and opt["errors"] == 0 and base["errors"] == 0
                and opt["ledger_ok"] and base["ledger_ok"]
                and not opt["compiled_over_budget"]
                and not base["compiled_over_budget"]
                and opt.get("spec_accepted", 0) > 0
                # the spec arm must beat no-spec on BOTH axes at
                # BOTH temperatures
                and opt["client_tokens_per_sec"]
                > base["client_tokens_per_sec"]
                and opt["ttft_p95_s"] < base["ttft_p95_s"]
            )
        result["repeats"] = reps
        # headline keys in the shared BENCH_*.json shape (the perf
        # trajectory tracker scans recorded files for these)
        result["value"] = result["spec_greedy"]["client_tokens_per_sec"]
        result["serve_toks_per_sec"] = result["value"]
        result["serve_ttft_p95"] = result["spec_greedy"]["ttft_p95_s"]
        result["ttft_p95_baseline_s"] = (
            result["nospec_greedy_baseline"]["ttft_p95_s"]
        )
        print(json.dumps(result))
        return 0 if ok else 1
    if args.prefix:
        result = {
            "metric": "prefix_tokens_per_sec",
            "unit": "tokens/s",
        }
        # best-of-N per arm, interleaved (same rationale as --engine);
        # 4 reps, not 3: on the nproc=1 CI box single runs of either
        # arm swing ~2x on OS noise, and the comparison is between the
        # arms' CEILINGS — the radix ceiling is ~1.5x the baseline's,
        # but 3 reps occasionally miss it while the baseline lands its
        # golden run
        reps = max(1, int(os.environ.get(
            "TPUSLICE_PREFIX_REPEATS", "4")))
        # throwaway process-warming run (see smoke_engine)
        bench_prefix(radix=False, requests=6, seed=args.prefix_seed)
        opts, bases = [], []
        for _ in range(reps):
            opts.append(
                bench_prefix(radix=True, seed=args.prefix_seed)
            )
            bases.append(
                bench_prefix(radix=False, seed=args.prefix_seed)
            )
        opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
        base = max(bases, key=lambda r: r["client_tokens_per_sec"])
        result["prefix_radix"] = opt
        result["prefix_exact_match_baseline"] = base
        result["repeats"] = reps
        result["tokens_per_sec_runs"] = {
            "radix": [r["client_tokens_per_sec"] for r in opts],
            "exact_match": [r["client_tokens_per_sec"]
                            for r in bases],
        }
        result["value"] = opt["client_tokens_per_sec"]
        if base["client_tokens_per_sec"]:
            result["vs_baseline"] = round(
                opt["client_tokens_per_sec"]
                / base["client_tokens_per_sec"], 2
            )
        # headline keys in the shared BENCH_*.json shape (the perf
        # trajectory tracker scans recorded files for these)
        result["serve_toks_per_sec"] = opt["client_tokens_per_sec"]
        result["serve_ttft_p95"] = opt["ttft_p95_s"]
        result["ttft_p95_baseline_s"] = base["ttft_p95_s"]
        print(json.dumps(result))
        ok = (
            opt["hung"] == 0 and base["hung"] == 0
            and opt["errors"] == 0 and base["errors"] == 0
            and opt["ledger_ok"] and base["ledger_ok"]
            and opt["prefix_tokens_saved"] > 0
            # the radix arm must beat exact-match on BOTH axes
            and opt["client_tokens_per_sec"]
            > base["client_tokens_per_sec"]
            and opt["ttft_p95_s"] < base["ttft_p95_s"]
        )
        return 0 if ok else 1
    if args.engine:
        result = {
            "metric": "engine_tokens_per_sec",
            "unit": "tokens/s",
        }
        # best-of-N per arm, interleaved (same rationale as --serving:
        # single samples flip on OS noise on shared-core CI boxes)
        reps = max(1, int(os.environ.get(
            "TPUSLICE_ENGINE_REPEATS", "3")))
        # throwaway process-warming run (see smoke_engine)
        bench_engine(optimized=False, requests=6, seed=args.engine_seed)
        opts, bases = [], []
        for _ in range(reps):
            opts.append(
                bench_engine(optimized=True, seed=args.engine_seed)
            )
            bases.append(
                bench_engine(optimized=False, seed=args.engine_seed)
            )
        opt = max(opts, key=lambda r: r["client_tokens_per_sec"])
        base = max(bases, key=lambda r: r["client_tokens_per_sec"])
        result["engine_optimized"] = opt
        result["engine_per_slot_baseline"] = base
        result["repeats"] = reps
        result["tokens_per_sec_runs"] = {
            "optimized": [r["client_tokens_per_sec"] for r in opts],
            "per_slot": [r["client_tokens_per_sec"] for r in bases],
        }
        result["value"] = opt["client_tokens_per_sec"]
        if base["client_tokens_per_sec"]:
            result["vs_baseline"] = round(
                opt["client_tokens_per_sec"]
                / base["client_tokens_per_sec"], 2
            )
        # TTFT p95 compared at best-tok/s runs; the headline keys ride
        # the shared BENCH_*.json shape for the perf trajectory
        result["serve_toks_per_sec"] = opt["client_tokens_per_sec"]
        result["serve_ttft_p95"] = opt["ttft_p95_s"]
        result["ttft_p95_baseline_s"] = base["ttft_p95_s"]
        print(json.dumps(result))
        ok = (
            opt["hung"] == 0 and base["hung"] == 0
            and opt["errors"] == 0 and base["errors"] == 0
            and opt["ledger_ok"] and base["ledger_ok"]
            # the hot path must beat the per-slot arm on BOTH axes
            and opt["client_tokens_per_sec"]
            > base["client_tokens_per_sec"]
            and opt["ttft_p95_s"] < base["ttft_p95_s"]
        )
        return 0 if ok else 1
    if args.serving:
        result = {
            "metric": "serving_tokens_per_sec",
            "unit": "tokens/s",
        }
        # best-of-N per arm, interleaved: the arms run identical
        # workloads, so on a noisy shared-core machine (CI is nproc=1)
        # the best observation per arm is the one least polluted by OS
        # scheduling — a single-sample comparison flips on noise alone
        reps = max(1, int(os.environ.get(
            "TPUSLICE_SERVING_REPEATS", "2")))
        conts, fixeds = [], []
        for _ in range(reps):
            conts.append(
                bench_serving(mode="continuous", seed=args.serving_seed)
            )
            fixeds.append(
                bench_serving(mode="fixed", seed=args.serving_seed)
            )
        cont = max(conts, key=lambda r: r["client_tokens_per_sec"])
        fixed = max(fixeds, key=lambda r: r["client_tokens_per_sec"])
        result["serving_continuous"] = cont
        result["serving_fixed_baseline"] = fixed
        result["repeats"] = reps
        result["tokens_per_sec_runs"] = {
            "continuous": [r["client_tokens_per_sec"] for r in conts],
            "fixed": [r["client_tokens_per_sec"] for r in fixeds],
        }
        result["value"] = cont["client_tokens_per_sec"]
        if fixed["client_tokens_per_sec"]:
            result["vs_baseline"] = round(
                cont["client_tokens_per_sec"]
                / fixed["client_tokens_per_sec"], 2
            )
        result["gold_ttft_p95_s"] = cont["gold_ttft_p95_s"]
        result["gold_ttft_p95_baseline_s"] = fixed["gold_ttft_p95_s"]
        result["kv_util_mean"] = cont["kv_util_mean"]
        # headline keys in the shared BENCH_*.json shape: the perf
        # trajectory tracker scans recorded files for these flat
        # numerics, so r10 and later serving records register
        # automatically
        result["serve_toks_per_sec"] = cont["client_tokens_per_sec"]
        result["serve_ttft_p95"] = cont["ttft_p95_s"]
        print(json.dumps(result))
        ok = (
            cont["hung"] == 0 and fixed["hung"] == 0
            and cont["errors"] == 0
            # continuous beats the fixed-round baseline on sustained
            # useful tok/s at equal capacity...
            and cont["client_tokens_per_sec"]
            > fixed["client_tokens_per_sec"]
            # ...and keeps the latency class inside its TTFT SLO while
            # best-effort degrades gracefully (still terminates)
            and cont["gold_ttft_p95_s"] <= cont["gold_ttft_slo_s"]
        )
        return 0 if ok else 1
    if args.defrag:
        result = {
            "metric": "defrag_capacity_utilization",
            "unit": "fraction",
        }
        after = bench_defrag(
            policy="frag-aware", repack=True, seed=args.defrag_seed,
        )
        # the baseline arm never recovers; a short censoring timeout
        # keeps the tier fast — its p95 is a floor, not a measurement
        before = bench_defrag(
            policy="first-fit", repack=False, seed=args.defrag_seed,
            timeout=8.0,
        )
        chaos = bench_defrag(
            policy="frag-aware", repack=True, seed=args.defrag_seed,
            timeout=60.0, chaos=True,
        )
        result["defrag"] = after
        result["defrag_baseline"] = before
        result["defrag_chaos"] = chaos
        result["value"] = after["util_after"]
        if before["util_after"]:
            result["vs_baseline"] = round(
                after["util_after"] / before["util_after"], 2
            )
        result["nocap_wait_p95_s"] = after["nocap_wait_p95_s"]
        result["nocap_wait_p95_baseline_s"] = before["nocap_wait_p95_s"]
        print(json.dumps(result))
        ok = (
            not after["chain_errors"]
            and not chaos["chain_errors"]
            and after["big_granted"] == after["big_pods"]
            and chaos["big_granted"] == chaos["big_pods"]
            and after["util_after"] > before["util_after"]
            and after["nocap_wait_p95_s"] < before["nocap_wait_p95_s"]
        )
        return 0 if ok else 1
    if args.scale:
        result = {"metric": "scale_grants_per_sec", "unit": "grants/sec"}
        scale = bench_scale(n_nodes=args.nodes, n_pods=args.pods)
        result["scale"] = scale
        result["value"] = scale["grants_per_sec"]
        if args.scale_baseline:
            # the serial re-list control plane is orders of magnitude
            # slower; measure it over a smaller burst and compare rates
            base = bench_scale(
                n_nodes=args.nodes,
                n_pods=min(args.pods, args.baseline_pods),
                baseline=True,
                timeout=1200.0,
            )
            result["scale_baseline"] = base
            if base["grants_per_sec"]:
                result["vs_baseline"] = round(
                    scale["grants_per_sec"] / base["grants_per_sec"], 1
                )
        print(json.dumps(result))
        return 0

    try:
        cp = bench_control_plane()
    except Exception as e:
        print(f"FATAL: control-plane bench failed: {e}", file=sys.stderr)
        return 1

    p50 = cp["p50_s"]
    result = {
        "metric": "slice_grant_p50_latency",
        "value": p50,
        "unit": "seconds",
        "vs_baseline": round(BASELINE_S / p50, 1) if p50 > 0 else 0,
        # the full latency/throughput shape shared with the scale tier
        "slice_grant_p95_latency": cp["p95_s"],
        "slice_grant_p99_latency": cp["p99_s"],
        "slice_grants_per_sec": cp["grants_per_sec"],
    }
    try:
        http_cp = bench_control_plane(transport="http")
        result["slice_grant_p50_latency_http"] = http_cp["p50_s"]
        result["slice_grant_p99_latency_http"] = http_cp["p99_s"]
    except Exception as e:  # noqa: BLE001 - report alongside, don't kill
        result["slice_grant_http_error"] = f"{type(e).__name__}: {e}"
    result.update(bench_tpu())
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
