"""Benchmark entry: control-plane grant latency + on-chip workload numbers.

Headline (BASELINE.md): slice-grant p50 latency (request → pod Running),
target < 60 s for a dynamically carved slice (the reference publishes no
numbers at all — its only anecdote is a 15 s gated-pod→Running AGE in a
demo transcript, ``/root/reference/README.md:200-203``). This drives the
full control loop — gated pod → controller placement → CR fan-out → agent
realization on the device backend → ConfigMap handoff → ungate →
scheduler bind — on a simulated two-node v5e-16 torus under a
mixed-profile load, and reports the p50 over all grants.

Secondary (BASELINE.md "measure & report"): decode tokens/sec/chip, train
MFU, and the compiled pallas flash kernel vs XLA — measured on the real
chip by ``instaslice_tpu/bench_tpu.py`` in a subprocess with a hard
timeout. A missing or hung TPU is a REPORTED error in the output
(``tpu_error``), never a silent CPU fallback.

Prints ONE JSON line. The required keys ({"metric", "value", "unit",
"vs_baseline"}) carry the headline; the TPU numbers ride alongside.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

BASELINE_S = 60.0
# mixed load from BASELINE.json configs[3]: 8 concurrent pods, mixed
# {1x1, 2x1, 2x2} on one v5e-16 (two hosts, 4x4 torus); run 3 waves.
# 14 of 16 chips per wave — concurrent but not a perfect-packing puzzle.
WAVE = ["v5e-2x2", "v5e-2x1", "v5e-2x1", "v5e-2x1",
        "v5e-1x1", "v5e-1x1", "v5e-1x1", "v5e-1x1"]
WAVES = 3

#: wall budget for the on-chip half; first compiles are ~20-40 s each.
TPU_BENCH_TIMEOUT = float(os.environ.get("TPUSLICE_TPU_BENCH_TIMEOUT", "900"))


def bench_control_plane() -> float:
    """Slice-grant p50 over 3 mixed waves on the 2-node sim. Pure control
    plane — no jax, no chip."""
    from instaslice_tpu.sim import SimCluster

    grants = []
    with SimCluster(n_nodes=2, generation="v5e",
                    deletion_grace_seconds=0.2) as c:
        for wave in range(WAVES):
            names = []
            t0 = {}
            for i, profile in enumerate(WAVE):
                name = f"bench-{wave}-{i}"
                t0[name] = time.monotonic()
                c.submit(name, profile=profile)
                names.append(name)
            for name in names:
                if not c.wait_phase(name, "Running", timeout=90):
                    raise RuntimeError(
                        f"{name} never reached Running "
                        f"(phase={c.pod_phase(name)})"
                    )
                grants.append(time.monotonic() - t0[name])
            for name in names:
                c.delete_pod(name)
            for name in names:
                c.wait_gone(name, timeout=60)
    return statistics.median(grants)


def bench_tpu() -> dict:
    """Run the on-chip bench in a subprocess so a hung TPU tunnel (or a
    missing chip) becomes a reported error, not a wedged bench."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "instaslice_tpu.bench_tpu"],
            capture_output=True,
            timeout=TPU_BENCH_TIMEOUT,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"tpu_error": (
            f"TPU bench exceeded {TPU_BENCH_TIMEOUT:.0f}s "
            "(chip unreachable or tunnel hung)"
        )}
    lines = (proc.stdout or b"").decode().strip().splitlines()
    out: dict = {}
    parsed = False
    for line in reversed(lines):  # last JSON line wins; skip stray prints
        try:
            out = json.loads(line)
            parsed = True
            break
        except ValueError:
            continue
    if not parsed:
        out["error"] = (
            f"TPU bench emitted no JSON (rc={proc.returncode}): "
            + (proc.stderr or proc.stdout or b"").decode()[-300:]
        )
    elif proc.returncode != 0 and "error" not in out:
        out["error"] = (proc.stderr or b"").decode()[-300:]
    if "error" in out:
        return {"tpu_error": out.pop("error"), **out}
    return out


def main() -> int:
    try:
        p50 = bench_control_plane()
    except Exception as e:
        print(f"FATAL: control-plane bench failed: {e}", file=sys.stderr)
        return 1

    result = {
        "metric": "slice_grant_p50_latency",
        "value": round(p50, 4),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_S / p50, 1) if p50 > 0 else 0,
    }
    result.update(bench_tpu())
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
