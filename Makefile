# Build plane (reference analog: Makefile:79-174 — build / docker-build /
# install / deploy / test / test-e2e via kustomize + controller-gen; here
# the manifests are generated from Python and the native lib via make).

PY ?= python
IMG_PREFIX ?= instaslice-tpu
TAG ?= latest
KUBECTL ?= kubectl
PROTOC ?= protoc

.PHONY: all
all: native manifests test

# ---------------------------------------------------------------- codegen

.PHONY: manifests
manifests:  ## Regenerate config/crd/bases from instaslice_tpu.api.crd
	$(PY) tools/gen_manifests.py

.PHONY: proto
proto:  ## Regenerate device-plugin protobuf messages
	$(PROTOC) -I instaslice_tpu/deviceplugin/proto \
	  --python_out=instaslice_tpu/deviceplugin \
	  instaslice_tpu/deviceplugin/proto/deviceplugin.proto

# ----------------------------------------------------------------- native

.PHONY: native
native:  ## Build libtpuslice.so + its C++ test binary
	$(MAKE) -C native

.PHONY: native-test
native-test: native
	native/build/tpuslice_test

# ------------------------------------------------------------------ tests

.PHONY: lint
lint:  ## Project-invariant static analysis (docs/STATIC_ANALYSIS.md): zero tolerance — any finding fails the build
	$(PY) tools/slicelint.py

.PHONY: check
check: lint  ## Both static gates: slicelint (per-file idiom) + slicecheck (whole-program guarded-by + dispatch hygiene, docs/STATIC_ANALYSIS.md) — zero tolerance
	$(PY) tools/slicecheck.py

.PHONY: test
test: check  ## Fast tier (~2 min): slicelint gate, control plane, device, kube, topology — then the trace-check + events-check + telemetry-smoke + profile-smoke observability gates and the bench-smoke + bench-defrag-smoke + bench-serving-smoke + bench-engine-smoke + bench-prefix-smoke + bench-spec-smoke + bench-router-smoke floors
	$(PY) -m pytest tests/ -x -q -m "not slow"
	$(MAKE) trace-check
	$(MAKE) events-check
	$(MAKE) telemetry-smoke
	$(MAKE) profile-smoke
	$(MAKE) chaos-crash-smoke
	$(MAKE) chaos-partition-smoke
	$(MAKE) bench-smoke
	$(MAKE) bench-defrag-smoke
	$(MAKE) bench-serving-smoke
	$(MAKE) bench-engine-smoke
	$(MAKE) bench-prefix-smoke
	$(MAKE) bench-spec-smoke
	$(MAKE) bench-router-smoke

.PHONY: telemetry-smoke
telemetry-smoke:  ## <60 s fleet-telemetry gate (docs/OBSERVABILITY.md "Fleet telemetry"): 2-replica fleet behind the router + aggregator on a pinned clock, clean AND under one seeded delay-only fault plan — aggregator rollups reconcile EXACTLY with the loadgen client report and the journal counters, burn-rate High fires under the injected-latency arm and Clears on heal, a capacity-blocked request stitches a >=3-component timeline via the caused-by link, zero hung
	JAX_PLATFORMS=cpu timeout -k 10 300 $(PY) tools/telemetry_smoke.py

.PHONY: profile-smoke
profile-smoke:  ## <60 s continuous-profiler gate (docs/OBSERVABILITY.md "Profiling"): serve + loadgen with the profiler armed — tok/s >= 0.95x the unprofiled arm, profiler ring == scheduler round counter == profile_rounds metric with zero ring growth after quiesce, exported Chrome trace valid with >=1 full round lane, >=1 request waterfall stitched, zero mid-traffic CompileObserved after warmup
	JAX_PLATFORMS=cpu timeout -k 10 300 $(PY) tools/profile_smoke.py

.PHONY: bench-trend
bench-trend:  ## Bench-record trend report + regression gate: reads every BENCH*_rNN.json tier, prints the headline series, exits non-zero when the newest record of a tier regresses >10% vs the best prior record of that tier
	$(PY) tools/bench_trend.py

.PHONY: chaos-crash-smoke
chaos-crash-smoke:  ## <60 s crash-consistency gate (docs/RECOVERY.md): one controller kill mid-fan-out + one agent kill mid-realize + one serving-replica kill mid-stream, each under load — every pod granted, zero double-allocations, zero orphaned device slices, zero hung requests, chains legal across restart epochs
	JAX_PLATFORMS=cpu timeout -k 10 300 $(PY) -m pytest tests/test_crash_chaos.py -q -k "smoke" -p no:cacheprovider

.PHONY: chaos-partition-smoke
chaos-partition-smoke:  ## <60 s partition-tolerance gate (docs/RECOVERY.md "Partitions & gray failures"): partition the controller -> failover -> heal -> converge with zero double-allocations; agent static mode across a cut; eject a 100%-success gray replica on latency EWMA -> sessions migrate -> re-admit after heal — nemesis invariant checker strict, zero hung requests
	JAX_PLATFORMS=cpu timeout -k 10 300 $(PY) -m pytest tests/test_partition_chaos.py -q -k "smoke" -p no:cacheprovider

.PHONY: bench-smoke
bench-smoke:  ## <60 s shrunken scale run (sharded workers + informer plane on a fleet sim): asserts a grants/sec floor and zero reconcile errors (TPUSLICE_SMOKE_FLOOR/NODES/PODS to tune)
	JAX_PLATFORMS=cpu $(PY) bench.py --smoke

.PHONY: bench-defrag-smoke
bench-defrag-smoke:  ## <60 s churn run: fragment a group, assert the repacker recovers the utilization floor (TPUSLICE_DEFRAG_FLOOR), grants every blocked pod, and keeps every transition chain legal (events-check strict)
	JAX_PLATFORMS=cpu $(PY) bench.py --defrag-smoke

.PHONY: bench-defrag
bench-defrag:  ## Full defrag tier: frag-aware + repacker vs first-fit-no-repack (capacity utilization, NoCapacity-wait p95) plus the mid-migration chaos arm (docs/SCALING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --defrag

.PHONY: bench-serving-smoke
bench-serving-smoke:  ## <60 s mixed-SLO serving run over the continuous scheduler: asserts latency-class SLO attainment ≥ TPUSLICE_SERVING_SLO_FLOOR, paged kv utilization ≥ TPUSLICE_SERVING_KV_FLOOR (and > the legacy stripe metric), zero hung requests
	JAX_PLATFORMS=cpu $(PY) bench.py --serving-smoke

.PHONY: bench-serving
bench-serving:  ## Full serving tier: continuous-batching scheduler vs the fixed-decode-round baseline on the mixed-SLO multi-tenant scenario (tok/s, per-class TTFT p95, SLO attainment, paged-vs-legacy kv utilization) — records BENCH_SERVING_r09.json (docs/SERVING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --serving

.PHONY: bench-engine-smoke
bench-engine-smoke:  ## <60 s bursty-admission run of both engine arms: asserts hot-path (batched prefill + overlap) tok/s >= TPUSLICE_ENGINE_FLOOR x the per-slot baseline, zero hung requests, preempt/resume ledger reconciling
	JAX_PLATFORMS=cpu $(PY) bench.py --engine-smoke

.PHONY: bench-engine
bench-engine:  ## Full engine hot-path tier: batched-prefill + overlap arm vs the per-slot PR 9 baseline, best-of-3 per arm (tok/s AND TTFT p95 must both win) — records BENCH_ENGINE_r10.json (docs/SERVING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --engine

.PHONY: bench-prefix-smoke
bench-prefix-smoke:  ## <60 s shared-prefix run of both arms: asserts radix tok/s >= TPUSLICE_PREFIX_FLOOR (0.9, a regression floor — the recorded bench-prefix tier gates the strict win) x the exact-match baseline, prefix-hit token savings > 0, ledgers reconciling, zero leaked blocks after quiesce
	JAX_PLATFORMS=cpu $(PY) bench.py --prefix-smoke

.PHONY: bench-prefix
bench-prefix:  ## Full radix prefix-cache tier: radix arm vs exact-match-only baseline on the seeded shared-prefix workload, best-of-3 per arm (tok/s AND TTFT p95 must both win) — records BENCH_PREFIX_r11.json (docs/SERVING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --prefix

.PHONY: bench-spec-smoke
bench-spec-smoke:  ## <60 s speculative-decoding run of both arms at temperature>0: asserts spec tok/s >= TPUSLICE_SPEC_FLOOR (0.9, a regression floor — the recorded bench-spec tier gates the strict win) x the no-spec baseline, real draft acceptance, ledgers reconciling with zero leaked blocks/locks after quiesce, compiled programs <= budget
	JAX_PLATFORMS=cpu $(PY) bench.py --spec-smoke

.PHONY: bench-spec
bench-spec:  ## Full speculative-decoding tier: spec arm (rejection sampling + adaptive k + overlapped rounds) vs the no-spec baseline at temperature 0 AND >0, best-of-4 interleaved (tok/s AND TTFT p95 must both win at both temperatures) — records BENCH_SPEC_r12.json (docs/SERVING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --spec

.PHONY: bench-router-smoke
bench-router-smoke:  ## <60 s 2-replica fleet gate: router aggregate tok/s >= TPUSLICE_ROUTER_FLOOR (0.5, a meltdown floor; the deterministic gates are prefix routing firing, the migration probe, and clean ledgers — the recorded tier gates the capacity win) x the single replica on the identical recorded->replayed stream, one live KV session migration token-identical, zero hung, ledgers reconcile on both replicas
	JAX_PLATFORMS=cpu $(PY) bench.py --router-smoke

.PHONY: bench-router
bench-router:  ## Full fleet-router tier: 3-replica router vs best single replica on the identical recorded->replayed stream (fleet wins tok/s by TPUSLICE_ROUTER_RECORD_FLOOR with TTFT p95 no worse; the one-core CI box measures the prefix-capacity mechanism — see docs/SERVING.md) + churn arm (replica kill/re-add mid-run, migrated sessions oracle-exact, ledgers clean) — records BENCH_ROUTER_r13.json
	JAX_PLATFORMS=cpu $(PY) bench.py --router

.PHONY: bench-scale
bench-scale:  ## Fleet-scale control-plane bench: 1k nodes / 2k pending pods, grants/sec + gate→ungate p95/p99, with the serial re-list baseline ratio (docs/SCALING.md)
	JAX_PLATFORMS=cpu $(PY) bench.py --scale --scale-baseline

.PHONY: trace-check
trace-check:  ## Observability gate: drive the sim + a short loadgen with TPUSLICE_TRACE_FILE set, then validate the JSONL (unparseable lines, negative durations, orphan spans, broken trace propagation)
	@f=$$(mktemp -u /tmp/tpuslice-trace-check.XXXXXX.jsonl); \
	  echo "trace-check: $$f"; \
	  JAX_PLATFORMS=cpu $(PY) tools/validate_trace.py --drive $$f \
	    && rm -f $$f

.PHONY: events-check
events-check:  ## Flight-recorder gate: drive the sim (one clean grant + one injected-fault retry) and a serving drain cycle with TPUSLICE_EVENT_FILE set, then validate the journal (ordered transition chains, trace-id links, reason catalog, describe-pod rendering)
	@f=$$(mktemp -u /tmp/tpuslice-events-check.XXXXXX.jsonl); \
	  echo "events-check: $$f"; \
	  JAX_PLATFORMS=cpu $(PY) tools/validate_events.py --drive $$f \
	    && rm -f $$f

.PHONY: test-all
test-all:  ## Everything, incl. jax-workload + multi-process tiers (~19 min)
	$(PY) -m pytest tests/ -x -q

.PHONY: test-e2e
test-e2e:  ## Full in-process cluster lifecycle tier
	$(PY) -m pytest tests/test_e2e_lifecycle.py -q

.PHONY: test-e2e-kind
test-e2e-kind:  ## Real-cluster e2e on KinD (skips cleanly without docker/kind)
	./deploy/e2e_kind.sh

.PHONY: chaos
chaos:  ## Control-plane + serving + crash-consistency chaos tiers across 3 seeds (hung tests dump all thread stacks via faulthandler before the outer timeout kills them). The crash arm kill-loops every crash point (docs/RECOVERY.md). TPUSLICE_LOCKCHECK=1 arms the lock-order race detector: any ABBA cycle observed during the run fails the session (docs/STATIC_ANALYSIS.md)
	@set -e; for seed in 1 2 3; do \
	  echo "=== chaos seed $$seed ==="; \
	  CHAOS_SEED=$$seed CHAOS_DURATION=$${CHAOS_DURATION:-8} \
	  PYTEST_FAULTHANDLER_SESSION_TIMEOUT=330 \
	  JAX_PLATFORMS=cpu \
	  timeout -k 10 360 $(PY) -m pytest \
	    tests/test_chaos.py tests/test_serving_chaos.py \
	    tests/test_crash_chaos.py tests/test_partition_chaos.py -q; \
	done

.PHONY: bench
bench:  ## Headline benchmark: slice-grant p50 latency (one JSON line)
	$(PY) bench.py

.PHONY: verify-manifests
verify-manifests:  ## Fail if checked-in CRD yaml drifted from the code
	$(PY) tools/gen_manifests.py --check

# ----------------------------------------------------------------- images

.PHONY: docker-build
docker-build:  ## Controller, agent, and device-plugin images
	docker build -f Dockerfile.controller -t $(IMG_PREFIX)-controller:$(TAG) .
	docker build -f Dockerfile.agent -t $(IMG_PREFIX)-agent:$(TAG) .
	docker build -f Dockerfile.deviceplugin -t $(IMG_PREFIX)-deviceplugin:$(TAG) .

.PHONY: build-images
build-images:  ## Build the images with whatever builder exists; without one, execute the Dockerfiles' build steps on the host and log the proof (deploy/docker-build.log)
	$(PY) tools/build_images.py

# ----------------------------------------------------------------- deploy

.PHONY: install
install: manifests  ## Install the TpuSlice CRD
	$(KUBECTL) apply -f config/crd/bases/

.PHONY: uninstall
uninstall:
	$(KUBECTL) delete -f config/crd/bases/ --ignore-not-found

.PHONY: deploy
deploy: install  ## CRD + RBAC + controller/agent/device-plugin workloads
	$(KUBECTL) apply -k config/default

.PHONY: undeploy
undeploy:
	$(KUBECTL) delete -k config/default --ignore-not-found

.PHONY: test-deploy
test-deploy:  ## Deploy-plane validation without a cluster: render config/default, apply over HTTP to the fake apiserver, cross-check selectors/SAs/ports, lint Dockerfiles against pyproject scripts
	$(PY) tools/test_deploy.py > deploy/test-deploy.log 2>&1; \
	  st=$$?; cat deploy/test-deploy.log; exit $$st
