"""Control-plane flight recorder: a durable, queryable record of what
the control plane decided and why.

PR 2 gave the operator traces (how long things took) and metrics (how
often); this module adds the third observability pillar — *what
happened*: every allocation state transition, controller
admission/placement/no-capacity decision, agent realize/teardown,
device-plugin health flip, kube breaker/backoff stall, and serving
drain/shed becomes a structured :class:`Event` with a monotonic ``seq``,
an injected wall clock, the emitting ``component``, a ``reason``
constant from :mod:`instaslice_tpu.api.constants` (the ONE reason
catalog — slicelint's ``event-reason-literal`` rule enforces it), an
object reference, a human message, and the ``trace_id`` linking it into
PR 2's traces.

Events land in three places:

- a bounded in-memory ring (queryable from tests, the
  ``GET /v1/debug/events`` endpoints on the serving and probe HTTP
  planes, and ``tpuslice events``);
- an optional JSONL sink (``TPUSLICE_EVENT_FILE``) validated by
  ``tools/validate_events.py`` / ``make events-check``;
- ``tpuslice_events_total{component,reason}`` counters (+ a
  last-event-timestamp gauge) on :class:`~instaslice_tpu.metrics.
  metrics.EventMetrics`.

Pod-scoped decisions are additionally mirrored as Kubernetes ``Event``
objects via :func:`emit_pod_event`, so ``kubectl describe pod`` explains
why a pod is still gated without any project tooling installed.

Emission is thread-safe via the lockcheck factory and must never hurt
the control plane: an unknown reason logs one warning (it still
records), and a failed Kubernetes Event write is logged and dropped —
the journal observes reconciles, it never wedges them.
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import logging
import os
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

from instaslice_tpu.api.constants import (
    EVENT_REASONS,
    TRACE_ID_ANNOTATION,
)
from instaslice_tpu.utils.lockcheck import named_lock

log = logging.getLogger("instaslice_tpu.obs")

_warned_reasons: set = set()


def _warn_unknown_reason(reason: str) -> None:
    """One warning per unknown reason, not a raise: a typo'd reason must
    show up loudly in the log (and fail ``make events-check``), but an
    event emit can never be allowed to wedge a reconcile."""
    if reason not in _warned_reasons:
        _warned_reasons.add(reason)
        log.warning(
            "journal event reason %r is not in the "
            "instaslice_tpu.api.constants catalog — add it there "
            "(docs/OBSERVABILITY.md reason catalog)", reason,
        )


@dataclasses.dataclass
class Event:
    """One flight-recorder record."""

    seq: int                       # journal-wide monotonic
    ts: float                      # unix seconds (journal's clock)
    component: str                 # "controller" | "agent-<node>" | ...
    reason: str                    # constant from api/constants.py
    object_ref: str = ""           # "Pod/<ns>/<name>" | "alloc/<id>" | ...
    message: str = ""
    trace_id: str = ""             # links into the PR 2 trace
    attrs: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "seq": self.seq,
            "ts": round(self.ts, 6),
            "component": self.component,
            "reason": self.reason,
        }
        if self.object_ref:
            d["objectRef"] = self.object_ref
        if self.message:
            d["message"] = self.message
        if self.trace_id:
            d["traceId"] = self.trace_id
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    @staticmethod
    def from_dict(d: dict) -> "Event":
        return Event(
            seq=int(d.get("seq", 0)),
            ts=float(d.get("ts", 0.0)),
            component=d.get("component", ""),
            reason=d.get("reason", ""),
            object_ref=d.get("objectRef", ""),
            message=d.get("message", ""),
            trace_id=d.get("traceId", ""),
            attrs={k: str(v) for k, v in (d.get("attrs") or {}).items()},
        )


class Journal:
    """Bounded ring of events + optional JSONL sink + metrics counters.

    ``clock`` is injectable (tests pin timestamps); ``event_file``
    defaults from ``TPUSLICE_EVENT_FILE``. ``metrics`` is any holder
    with ``events``/``last_event_ts`` (an
    :class:`~instaslice_tpu.metrics.metrics.EventMetrics`); one with its
    own registry is created lazily when omitted."""

    def __init__(self, capacity: int = 4096,
                 event_file: Optional[str] = None,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None, max_mb: Optional[float] = None,
                 keep: Optional[int] = None) -> None:
        self._lock = named_lock("journal.ring")
        self._events: deque = deque(maxlen=capacity)
        self._counts: Dict[str, int] = {}
        self._seq = 0
        self.clock: Callable[[], float] = clock or time.time
        self._file = None
        # file writes get their own lock (same split as utils/trace.py):
        # a slow disk must not serialize every reconcile thread behind
        # the hot ring lock, and close() can never yank the handle
        # between the check and the write
        self._file_lock = named_lock("journal.file")
        path = event_file or os.environ.get("TPUSLICE_EVENT_FILE")
        self._path = path or None
        # size-based sink rotation: past max_mb the sink shifts to
        # <path>.1 … <path>.N and reopens fresh (0 = unbounded, the
        # pre-rotation behavior; keep bounds the shifted generations)
        if max_mb is None:
            try:
                max_mb = float(
                    os.environ.get("TPUSLICE_EVENT_FILE_MAX_MB", "0")
                )
            except ValueError:
                max_mb = 0.0
        if keep is None:
            try:
                keep = int(
                    os.environ.get("TPUSLICE_EVENT_FILE_KEEP", "3")
                )
            except ValueError:
                keep = 3
        self._max_bytes = int(max_mb * 1024 * 1024) if max_mb > 0 else 0
        self._keep = max(1, keep)
        if path:
            try:
                self._file = open(path, "a", buffering=1)
            except OSError as e:
                # best-effort by contract: a bad sink path degrades to
                # ring-only recording — it must never turn every
                # reconcile/request into an exception
                log.warning(
                    "cannot open TPUSLICE_EVENT_FILE %s (%s); events "
                    "record to the in-memory ring only", path, e,
                )
        if metrics is None:
            from instaslice_tpu.metrics.metrics import EventMetrics

            metrics = EventMetrics()
        self.metrics = metrics

    # ------------------------------------------------------------ emission

    def emit(self, component: str, *, reason: str, object_ref: str = "",
             message: str = "", trace_id: str = "", **attrs) -> Event:
        """Record one event. ``reason`` is keyword-only and must come
        from the api/constants.py catalog (slicelint enforces the call
        sites; unknown reasons warn once and still record)."""
        if reason not in EVENT_REASONS:
            _warn_unknown_reason(reason)
        with self._lock:
            self._seq += 1
            ev = Event(
                seq=self._seq,
                ts=self.clock(),
                component=component,
                reason=reason,
                object_ref=object_ref,
                message=message,
                trace_id=trace_id,
                attrs={k: str(v) for k, v in attrs.items()},
            )
            self._events.append(ev)
            self._counts[reason] = self._counts.get(reason, 0) + 1
            sink = self._file
        for m in [self.metrics] + attached_metrics():
            m.events.labels(component=component, reason=reason).inc()
            m.last_event_ts.labels(component=component).set(ev.ts)
        if sink is not None:
            line = json.dumps(ev.to_dict()) + "\n"
            with self._file_lock:
                if self._file is not None:
                    try:
                        self._file.write(line)
                        if (self._max_bytes
                                and self._file.tell() >= self._max_bytes):
                            self._rotate_locked()
                    except OSError as e:
                        # disk full / EROFS mid-run: drop the sink, keep
                        # the ring — and keep the control plane alive
                        log.warning(
                            "event sink write failed (%s); disabling "
                            "the JSONL sink", e,
                        )
                        self._file = None
        return ev

    def _rotate_locked(self) -> None:
        """Shift the sink one generation (``_file_lock`` held): the live
        file becomes ``<path>.1``, prior generations shift up, anything
        past ``keep`` is dropped, and a fresh live file opens. A
        rotation failure degrades to ring-only recording — the exact
        sink-write-failure contract, because a sink that cannot rotate
        would otherwise grow without the bound the operator asked for."""
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        try:
            for i in range(self._keep, 0, -1):
                src = self._path if i == 1 else f"{self._path}.{i - 1}"
                dst = f"{self._path}.{i}"
                if os.path.exists(src):
                    os.replace(src, dst)
            self._file = open(self._path, "a", buffering=1)
        except OSError as e:
            log.warning(
                "event sink rotation failed (%s); disabling the JSONL "
                "sink", e,
            )
            self._file = None

    # ------------------------------------------------------------ querying

    def events(self, reason: Optional[str] = None,
               object_ref: Optional[str] = None,
               trace_id: Optional[str] = None,
               component: Optional[str] = None,
               since_seq: Optional[int] = None) -> List[Event]:
        with self._lock:
            out = list(self._events)
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        if object_ref is not None:
            out = [e for e in out if e.object_ref == object_ref]
        if trace_id is not None:
            out = [e for e in out if e.trace_id == trace_id]
        if component is not None:
            out = [e for e in out if e.component == component]
        if since_seq is not None:
            out = [e for e in out if e.seq > since_seq]
        return out

    def tail(self, n: int = 50) -> List[Event]:
        with self._lock:
            return list(self._events)[-n:]

    def counts(self) -> Dict[str, int]:
        """Per-reason totals since construction (not ring-bounded)."""
        with self._lock:
            return dict(self._counts)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._counts.clear()

    def close(self) -> None:
        """Close the JSONL sink. Idempotent; a write racing close is
        dropped under the file lock, never an exception."""
        with self._file_lock:
            f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


#: Runner-attached metrics holders, MODULE-level so they follow the
#: process rather than one Journal instance: reset_journal() (test
#: isolation / env rebinding) swaps the default journal, and a runner's
#: /metrics counters must keep counting on the new one — the same
#: resolve-per-use hazard utils/reconcile.py documents for tracers.
_attached_metrics: List = []
_attach_lock = named_lock("journal.attach")


def attach_metrics(holder) -> None:
    """Count every journal emit (any instance, across resets) on
    ``holder`` too — an ``EventMetrics`` bound to a runner's /metrics
    registry. Attach, not replace: a process hosting both a controller
    and an agent runner keeps ``tpuslice_events_total`` on BOTH scrape
    registries. Counts start at attach time; detach on shutdown."""
    with _attach_lock:
        _attached_metrics.append(holder)


def detach_metrics(holder) -> None:
    """Undo :func:`attach_metrics` (runner shutdown). Without the
    detach, re-created runners (leader-election churn, test sessions)
    would accumulate dead registries that every later emit still pays
    to increment."""
    with _attach_lock:
        if holder in _attached_metrics:
            _attached_metrics.remove(holder)


def attached_metrics() -> List:
    with _attach_lock:
        return list(_attached_metrics)


_default: Optional[Journal] = None
_default_lock = named_lock("journal.default")


def get_journal() -> Journal:
    """Process-wide default journal (created lazily — re-reads
    ``TPUSLICE_EVENT_FILE`` at creation)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Journal()
        return _default


def reset_journal(journal: Optional[Journal] = None) -> None:
    """Swap the process-wide default (test isolation / env rebinding —
    the exact contract of ``trace.reset_tracer``). The old default's
    file handle is closed."""
    global _default
    with _default_lock:
        old, _default = _default, journal
    if old is not None:
        old.close()


# --------------------------------------------------- kubernetes mirroring


def _rfc3339(ts: float) -> str:
    return (
        datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"
    )


def emit_pod_event(client, namespace: str, pod_name: str, *, reason: str,
                   message: str, component: str, pod_uid: str = "",
                   trace_id: str = "", event_type: str = "Normal",
                   journal: Optional[Journal] = None, **attrs) -> Event:
    """Journal a pod-scoped decision AND mirror it as a Kubernetes
    ``Event`` on the pod (fake and real clients both route the ``Event``
    kind), so ``kubectl describe pod`` explains the wait. The mirror is
    best-effort: an API failure is logged and dropped — an event write
    must never wedge the reconcile that emitted it.

    The mirror is deliberately synchronous (callers and tests observe
    the Event immediately; no queue/thread lifecycle to manage). Under
    a degraded API server the real client's retry backoff makes the
    first few mirrors slow, but its circuit breaker then fails the rest
    fast (CircuitOpen) until the server recovers — the stall is bounded
    and the events are dropped, not queued into a thundering herd."""
    j = journal or get_journal()
    ev = j.emit(
        component, reason=reason,
        object_ref=f"Pod/{namespace}/{pod_name}",
        message=message, trace_id=trace_id, **attrs,
    )
    manifest = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "name": f"{pod_name}.{uuid.uuid4().hex[:12]}",
            "namespace": namespace,
            **({"annotations": {TRACE_ID_ANNOTATION: trace_id}}
               if trace_id else {}),
        },
        "involvedObject": {
            "kind": "Pod",
            "namespace": namespace,
            "name": pod_name,
            **({"uid": pod_uid} if pod_uid else {}),
        },
        "reason": reason,
        "message": message[:1024],
        "type": event_type,
        "source": {"component": component},
        "firstTimestamp": _rfc3339(ev.ts),
        "lastTimestamp": _rfc3339(ev.ts),
        "count": 1,
    }
    try:
        client.create("Event", manifest)
    except Exception:
        # best-effort by contract (injected kube faults land here too)
        log.debug("failed to mirror %s event for pod %s/%s",
                  reason, namespace, pod_name, exc_info=True)
    return ev


# ------------------------------------------------------- debug endpoint


def debug_events_payload(qs: Dict[str, List[str]],
                         journal: Optional[Journal] = None) -> dict:
    """The shared ``GET /v1/debug/events`` handler body (serving plane
    in serving/api_server.py, operator probe plane in utils/probes.py).
    ``qs`` is a ``urllib.parse.parse_qs`` dict; supported filters:
    ``reason``, ``object``, ``trace_id``, ``component``, ``since_seq``;
    ``n`` bounds the returned tail (default 100). Raises ValueError on
    malformed numbers (callers answer 400)."""
    j = journal or get_journal()

    def one(key: str) -> Optional[str]:
        val = (qs.get(key) or [""])[0]
        return val or None

    n = int((qs.get("n") or ["100"])[0])
    if n < 1:
        raise ValueError("n must be a positive integer")
    since = qs.get("since_seq")
    since_seq = int(since[0]) if since else None
    evs = j.events(
        reason=one("reason"), object_ref=one("object"),
        trace_id=one("trace_id"), component=one("component"),
        since_seq=since_seq,
    )
    return {
        "total": len(evs),
        "counts": j.counts(),
        "events": [e.to_dict() for e in evs[-n:]],
    }
