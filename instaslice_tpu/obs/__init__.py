"""Observability surfaces beyond traces/metrics: the control-plane
flight recorder (structured event journal + Kubernetes Event mirroring
+ per-allocation audit trail). See docs/OBSERVABILITY.md."""

from instaslice_tpu.obs.journal import (  # noqa: F401
    Event,
    Journal,
    attach_metrics,
    debug_events_payload,
    detach_metrics,
    emit_pod_event,
    get_journal,
    reset_journal,
)
