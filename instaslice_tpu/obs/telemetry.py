"""Fleet telemetry plane: the sensor layer over every other plane.

Every observability surface before this one is process-local — the PR 2
tracer, the PR 5 flight recorder, and the per-process Prometheus
registries each describe ONE router, replica, controller, or agent.
The system is now a fleet, and ROADMAP items 3 and 5 need what only a
fleet-wide view can provide: an autoscaler fed by end-to-end signals,
and a macro-bench whose headline numbers are fleet goodput, per-class
SLO attainment, and chip-hours per million requests. This module is
that view, in three parts (docs/OBSERVABILITY.md "Fleet telemetry"):

- :class:`TraceStitcher` — **cross-process trace stitching**. Spans
  collected from each component's ``GET /v1/debug/trace`` and from
  ``TPUSLICE_TRACE_FILE`` JSONL files merge into one store keyed by
  trace id, rendered as a single causal timeline per request. The
  demand→supply link rides the ``caused_by`` span/event attribute the
  controller journals at admission (api/constants.py
  ``CAUSED_BY_ANNOTATION``): a request that waited on ``NoCapacity``
  links its serving trace to the controller grant trace that unblocked
  it, so ONE timeline shows router → replica → controller → agent.

- :class:`FleetAggregator` — **metrics federation**. A periodic scrape
  of every ``/metrics`` + ``/v1/stats`` endpoint (replicas discovered
  live from the router's replica set, operator probe servers listed
  explicitly) summed into fleet rollups: goodput tokens/sec,
  per-tenant-class SLO attainment, KV pressure, and **chip-hours
  accounting** — chip-seconds integrated from allocation lifecycle
  events (``SliceUngated`` → ``SliceDeleted``/``SliceFailed``, chip
  count on the event) joined against served request counts into
  chip-hours per million requests.

- :class:`BurnRateMonitor` — **multi-window SLO burn-rate alerting**
  (the Google SRE workbook shape): the error-budget burn rate is
  evaluated over a fast window pair (5m + 1h, threshold 14.4) and a
  slow pair (1h + 6h, threshold 6); an alert fires only when BOTH
  windows of a pair burn past the pair's threshold, and clears when no
  pair does. Transitions land in the journal as ``SLOBurnRateHigh`` /
  ``SLOBurnRateCleared`` and on the ``tpuslice_fleet_*`` gauges. The
  clock is injectable, so the sim and the telemetry smoke drive the
  windows deterministically.

Everything is surfaced on the aggregator's own HTTP plane —
``GET /v1/fleet`` (rollups + burn state), ``GET /v1/fleet/trace?trace_
id=X`` (the stitched timeline), plus the standard ``/healthz`` /
``/readyz`` / ``/metrics`` / ``/v1/debug/*`` set — and through the
``tpuslice fleet`` CLI. Run via ``tpuslice-telemetry --router
http://host:8080 --probe http://host:8081 ...``.
"""

from __future__ import annotations

import argparse
import json
import logging
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from instaslice_tpu.api.constants import (
    REASON_SLICE_DELETED,
    REASON_SLICE_FAILED,
    REASON_SLICE_UNGATED,
    REASON_SLO_BURN_CLEARED,
    REASON_SLO_BURN_HIGH,
)
from instaslice_tpu.metrics.metrics import FleetMetrics, render
from instaslice_tpu.obs.journal import (
    Journal,
    debug_events_payload,
    get_journal,
)
from instaslice_tpu.obs.profiler import debug_profile_payload
from instaslice_tpu.utils.guards import guarded_by
from instaslice_tpu.utils.lockcheck import named_lock
from instaslice_tpu.utils.trace import debug_trace_payload, get_tracer

log = logging.getLogger("instaslice_tpu.obs.telemetry")


# ------------------------------------------------- exposition parsing

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+\d+)?\s*$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[Tuple[str, frozenset], float]:
    """Parse Prometheus text exposition into
    ``{(metric_name, frozenset(label items)): value}`` — the subset the
    federation needs (counters/gauges/histogram series; no metadata).
    Zero-dep by design: the aggregator must work in the same
    environments the ``_NoopMetric`` degradation path targets."""
    out: Dict[Tuple[str, frozenset], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        name, labels_raw, raw_val = m.groups()
        labels = {}
        if labels_raw:
            for lm in _LABEL.finditer(labels_raw):
                labels[lm.group(1)] = (
                    lm.group(2)
                    .replace('\\"', '"')
                    .replace("\\n", "\n")
                    .replace("\\\\", "\\")
                )
        try:
            val = float(raw_val)
        except ValueError:
            continue
        out[(name, frozenset(labels.items()))] = val
    return out


def metric_sum(samples: Dict[Tuple[str, frozenset], float], name: str,
               **match: str) -> float:
    """Sum every series of ``name`` whose labels include ``match``."""
    want = set(match.items())
    return sum(
        v for (n, labels), v in samples.items()
        if n == name and want <= set(labels)
    )


def metric_by_label(samples: Dict[Tuple[str, frozenset], float],
                    name: str, label: str,
                    **match: str) -> Dict[str, float]:
    """``{label value: summed value}`` across every series of ``name``
    matching ``match`` — per-tenant-class rollups in one call."""
    want = set(match.items())
    out: Dict[str, float] = {}
    for (n, labels), v in samples.items():
        if n != name or not want <= set(labels):
            continue
        d = dict(labels)
        if label in d:
            out[d[label]] = out.get(d[label], 0.0) + v
    return out


def merge_profile_summaries(summaries: List[dict]) -> Dict[str, dict]:
    """Conservative fleet merge of per-replica profiler segment
    summaries (obs/profiler.py ``segment_summary`` shape): counts sum;
    p50/p95/max take the max across replicas — a percentile of
    percentiles is not a percentile, so the fleet view reports the
    honest upper bound per segment instead of a fabricated quantile."""
    out: Dict[str, dict] = {}
    for summ in summaries:
        for name, row in (summ or {}).items():
            cur = out.setdefault(name, {
                "count": 0, "p50Ms": 0.0, "p95Ms": 0.0, "maxMs": 0.0,
            })
            cur["count"] += int(row.get("count", 0) or 0)
            for k in ("p50Ms", "p95Ms", "maxMs"):
                cur[k] = max(cur[k], float(row.get(k, 0.0) or 0.0))
    return out


# --------------------------------------------------- trace stitching

#: span-name prefix → the component that plane's spans belong to (the
#: prefixes are pinned by the docs/OBSERVABILITY.md span taxonomy)
_COMPONENT_ALIASES = {
    "repacker": "controller",
    "device": "agent",
    "engine": "serve",
}


def span_component(name: str) -> str:
    """Classify a span into its emitting component by name prefix
    (``controller.allocate`` → controller, ``serve.request`` → serve,
    ``router.route`` → router, ...)."""
    head = name.split(".", 1)[0]
    return _COMPONENT_ALIASES.get(head, head)


class TraceStitcher:
    """Merge spans from many processes/files into per-trace timelines.

    Spans dedupe on ``(traceId, spanId)`` — the same span arriving via
    a debug endpoint AND a trace file records once. ``caused_by``
    attributes (on ``controller.allocate`` spans and ``Admitted``
    journal events) build the demand→supply link map: grant trace →
    the serving trace it unblocked."""

    # spans arrive from the aggregator poll thread, debug-endpoint
    # handlers, and file ingestion — all merge under telemetry.stitch
    _spans: guarded_by("telemetry.stitch")
    _caused_by: guarded_by("telemetry.stitch")

    def __init__(self) -> None:
        self._lock = named_lock("telemetry.stitch")
        #: trace id → {span id → span dict}
        self._spans: Dict[str, Dict[str, dict]] = {}
        #: grant trace id → serving trace id it was caused by
        self._caused_by: Dict[str, str] = {}

    def add_span(self, span: dict) -> None:
        tid = span.get("traceId") or ""
        sid = span.get("spanId") or ""
        if not tid or not sid:
            return
        with self._lock:
            self._spans.setdefault(tid, {})[sid] = span
            cb = (span.get("attrs") or {}).get("caused_by")
            if cb:
                self._caused_by[tid] = str(cb)

    def add_event(self, event: dict) -> None:
        """Journal events carry the causality stamp too — the
        ``Admitted`` event's ``caused_by`` attr links its grant trace
        even when the span ring has already rotated the span out."""
        cb = (event.get("attrs") or {}).get("caused_by")
        tid = event.get("traceId") or ""
        if cb and tid:
            with self._lock:
                self._caused_by[tid] = str(cb)

    def ingest_debug_payload(self, payload: dict) -> int:
        """Feed a ``GET /v1/debug/trace`` response (either shape)."""
        n = 0
        for key in ("recent", "slowest", "spans"):
            for span in payload.get(key) or []:
                self.add_span(span)
                n += 1
        return n

    def ingest_file(self, path: str) -> int:
        """Feed a ``TPUSLICE_TRACE_FILE`` JSONL file."""
        n = 0
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self.add_span(json.loads(line))
                        n += 1
                    except (json.JSONDecodeError, TypeError):
                        continue
        except OSError as e:
            log.warning("cannot read trace file %s: %s", path, e)
        return n

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._spans)

    def spans(self, trace_id: str) -> List[dict]:
        with self._lock:
            out = list(self._spans.get(trace_id, {}).values())
        return sorted(out, key=lambda s: s.get("start", 0.0))

    def links_into(self, trace_id: str) -> List[str]:
        """Grant traces whose ``caused_by`` names ``trace_id``."""
        with self._lock:
            return sorted(
                g for g, s in self._caused_by.items() if s == trace_id
            )

    def caused_by(self, trace_id: str) -> Optional[str]:
        with self._lock:
            return self._caused_by.get(trace_id)

    def components(self, trace_id: str,
                   follow_links: bool = True) -> List[str]:
        comps = {
            span_component(s.get("name", ""))
            for s in self.spans(trace_id)
        }
        if follow_links:
            for g in self.links_into(trace_id):
                comps |= {
                    span_component(s.get("name", ""))
                    for s in self.spans(g)
                }
        return sorted(c for c in comps if c)

    def timeline(self, trace_id: str) -> dict:
        """The single causal timeline: the trace's own spans in start
        order plus every grant trace linked into it via ``caused_by``
        (the supply-side work a blocked request caused), all under the
        one requested root."""
        spans = self.spans(trace_id)
        linked = [
            {
                "traceId": g,
                "via": "caused_by",
                "spans": self.spans(g),
            }
            for g in self.links_into(trace_id)
        ]
        return {
            "traceId": trace_id,
            "spans": spans,
            "linked": linked,
            "components": self.components(trace_id),
            "spanCount": len(spans) + sum(
                len(x["spans"]) for x in linked
            ),
        }

    def orphans(self) -> List[dict]:
        """Spans whose ``parentId`` is missing from their own trace
        ACROSS every ingested source — the fleet-level propagation
        check ``tools/validate_trace.py --fleet`` runs. Per-file
        validation can pass while the fleet view is broken (the parent
        lives in a file that was never collected); this is the check
        that catches it."""
        out = []
        with self._lock:
            for tid, by_sid in self._spans.items():
                for span in by_sid.values():
                    pid = span.get("parentId")
                    if pid and pid not in by_sid:
                        out.append(span)
        return out


# ------------------------------------------------ chip-hours ledger


class ChipHoursAccountant:
    """Integrate chip-seconds from allocation lifecycle events.

    ``SliceUngated`` opens an interval (the slice is serving from here),
    ``SliceDeleted``/``SliceFailed`` closes it; the chip count rides
    the event (api/types.py stamps it on every transition). Live
    allocations accrue to "now" so the gauge never under-reports a
    long-running fleet."""

    def __init__(self, clock: Callable[[], float] = time.time) -> None:
        self.clock = clock
        self._closed_chip_seconds = 0.0
        #: alloc object_ref → (ungated ts, chips)
        self._live: Dict[str, Tuple[float, int]] = {}

    def add_event(self, event: dict) -> None:
        reason = event.get("reason", "")
        ref = event.get("objectRef", "")
        if not ref.startswith("alloc/"):
            return
        ts = float(event.get("ts", 0.0))
        if reason == REASON_SLICE_UNGATED:
            try:
                chips = int((event.get("attrs") or {}).get("chips", 0))
            except (TypeError, ValueError):
                chips = 0
            if chips > 0:
                self._live[ref] = (ts, chips)
        elif reason in (REASON_SLICE_DELETED, REASON_SLICE_FAILED):
            started = self._live.pop(ref, None)
            if started is not None:
                t0, chips = started
                self._closed_chip_seconds += max(0.0, ts - t0) * chips

    def chip_seconds(self, now: Optional[float] = None) -> float:
        now = self.clock() if now is None else now
        live = sum(
            max(0.0, now - t0) * chips
            for t0, chips in self._live.values()
        )
        return self._closed_chip_seconds + live

    def chips_live(self) -> int:
        return sum(chips for _, chips in self._live.values())


# ------------------------------------------------ burn-rate monitor

#: (short window s, long window s, burn threshold) — the SRE-workbook
#: multiwindow pairs: the fast pair catches a cliff in minutes, the
#: slow pair catches a slow leak without paging on noise; both windows
#: of a pair must burn past the threshold to fire
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 3600.0, 14.4),
    (3600.0, 21600.0, 6.0),
)


def _window_label(seconds: float) -> str:
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{seconds:g}s"


class BurnRateMonitor:
    """Multi-window error-budget burn-rate evaluation over cumulative
    per-class (missed, served) counters.

    ``observe`` records one federation sample per class;
    ``evaluate`` computes, per window, ``burn = (1 - attainment over
    the window) / (1 - target)`` and fires/clears per the window
    pairs. Transitions journal ``SLOBurnRateHigh`` /
    ``SLOBurnRateCleared`` (component ``telemetry``) and every rate
    lands on the ``tpuslice_fleet_slo_burn_rate`` gauge."""

    def __init__(self, target: float = 0.99,
                 windows: Tuple[Tuple[float, float, float], ...] =
                 DEFAULT_BURN_WINDOWS,
                 clock: Callable[[], float] = time.time,
                 journal: Optional[Journal] = None,
                 metrics: Optional[FleetMetrics] = None) -> None:
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1)")
        self.target = target
        self.windows = tuple(windows)
        self.clock = clock
        self._journal = journal
        self.metrics = metrics or FleetMetrics()
        #: class → deque[(ts, missed cumulative, served cumulative)]
        self._hist: Dict[str, deque] = {}
        self._burning: Dict[str, bool] = {}
        # history only needs to cover the longest window (plus one
        # pre-window sample for the delta base)
        self._horizon = max(
            (w[1] for w in self.windows), default=21600.0
        )

    def _j(self) -> Journal:
        return self._journal if self._journal is not None \
            else get_journal()

    def observe(self, tenant_class: str, missed: float,
                served: float) -> None:
        now = self.clock()
        hist = self._hist.setdefault(tenant_class, deque())
        hist.append((now, float(missed), float(served)))
        while len(hist) > 2 and hist[1][0] < now - self._horizon:
            hist.popleft()

    def _burn_over(self, hist: deque, now: float,
                   window: float) -> float:
        """Burn rate over [now - window, now]: the cumulative-counter
        delta between the newest sample and the newest sample at or
        before the window start (the oldest retained sample stands in
        when history is shorter than the window)."""
        if not hist:
            return 0.0
        newest = hist[-1]
        base = hist[0]
        cutoff = now - window
        for sample in hist:
            if sample[0] <= cutoff:
                base = sample
            else:
                break
        d_missed = newest[1] - base[1]
        d_served = newest[2] - base[2]
        if d_served <= 0:
            return 0.0
        return (d_missed / d_served) / (1.0 - self.target)

    def evaluate(self) -> Dict[str, dict]:
        """One evaluation pass over every observed class. Returns
        ``{class: {"burning": bool, "rates": {window label: burn},
        "fired": [pair labels]}}`` and journals transitions."""
        now = self.clock()
        out: Dict[str, dict] = {}
        for cls, hist in sorted(self._hist.items()):
            rates: Dict[str, float] = {}
            fired: List[str] = []
            for short, long_, threshold in self.windows:
                b_short = self._burn_over(hist, now, short)
                b_long = self._burn_over(hist, now, long_)
                rates[_window_label(short)] = round(b_short, 3)
                rates[_window_label(long_)] = round(b_long, 3)
                if b_short >= threshold and b_long >= threshold:
                    fired.append(
                        f"{_window_label(short)}/{_window_label(long_)}"
                    )
            burning = bool(fired)
            was = self._burning.get(cls, False)
            self._burning[cls] = burning
            for label, rate in rates.items():
                self.metrics.burn_rate.labels(
                    tenant_class=cls, window=label
                ).set(rate)
            self.metrics.burning.labels(tenant_class=cls).set(
                1.0 if burning else 0.0
            )
            if burning and not was:
                self._j().emit(
                    "telemetry", reason=REASON_SLO_BURN_HIGH,
                    object_ref=f"class/{cls}",
                    message=(
                        f"SLO burn rate high for class {cls!r}: "
                        f"pairs {', '.join(fired)} past threshold "
                        f"(target {self.target:g})"
                    ),
                    tenant_class=cls, pairs=",".join(fired),
                )
            elif was and not burning:
                self._j().emit(
                    "telemetry", reason=REASON_SLO_BURN_CLEARED,
                    object_ref=f"class/{cls}",
                    message=(
                        f"SLO burn rate recovered for class {cls!r}"
                    ),
                    tenant_class=cls,
                )
            out[cls] = {"burning": burning, "rates": rates,
                        "fired": fired}
        return out

    def burning(self) -> Dict[str, bool]:
        return dict(self._burning)


# --------------------------------------------------- the aggregator


def _http_get(url: str, timeout: float) -> Tuple[int, bytes]:
    req = urllib.request.Request(url, method="GET")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(url: str, timeout: float) -> dict:
    _, body = _http_get(url, timeout)
    out = json.loads(body or b"{}")
    if not isinstance(out, dict):
        raise ValueError(f"{url} returned a non-object")
    return out


class FleetAggregator:
    """Scrape → federate → evaluate, one ``poll()`` per cycle.

    Replica endpoints are the static ``replica_urls`` plus whatever the
    router's ``/v1/stats`` replica set advertises at each poll (the
    fleet is elastic; discovery must be too). ``probe_urls`` are
    operator probe servers (controller/agent planes) — their
    ``/v1/debug/events`` feed chip-hours accounting and the causality
    link map, their ``/v1/debug/trace`` feeds the stitcher.
    ``event_files``/``trace_files`` ingest the JSONL sinks directly
    for offline runs. Everything tolerates a dead endpoint: a scrape
    error is counted and skipped, never raised."""

    # thread model: one poll at a time (the loop thread, or a test
    # driving poll() directly with the loop stopped) owns the scrape
    # bookkeeping; only the published rollup crosses to the HTTP
    # export handlers, under telemetry.fleet
    _fleet: guarded_by("telemetry.fleet")
    _seen_events: unguarded("poll-thread owned: ingestion only runs "
                            "inside _poll_inner")
    _last_tokens: unguarded("poll-thread owned: see _seen_events")
    _scrapes: unguarded("poll-thread owned counters; the rollup "
                        "exports a dict() copy taken on that thread")

    def __init__(self, router_url: Optional[str] = None,
                 replica_urls: Tuple[str, ...] = (),
                 probe_urls: Tuple[str, ...] = (),
                 trace_files: Tuple[str, ...] = (),
                 event_files: Tuple[str, ...] = (),
                 interval: float = 2.0,
                 slo_target: float = 0.99,
                 burn_windows: Tuple[Tuple[float, float, float], ...] =
                 DEFAULT_BURN_WINDOWS,
                 metrics: Optional[FleetMetrics] = None,
                 journal: Optional[Journal] = None,
                 clock: Callable[[], float] = time.time,
                 http_timeout: float = 3.0) -> None:
        self.router_url = (router_url or "").rstrip("/") or None
        self.replica_urls = tuple(u.rstrip("/") for u in replica_urls)
        self.probe_urls = tuple(u.rstrip("/") for u in probe_urls)
        self.trace_files = tuple(trace_files)
        self.event_files = tuple(event_files)
        self.interval = interval
        self.http_timeout = http_timeout
        self.clock = clock
        self.metrics = metrics or FleetMetrics()
        self._journal = journal
        self.stitcher = TraceStitcher()
        self.chip_hours = ChipHoursAccountant(clock=clock)
        self.burn = BurnRateMonitor(
            target=slo_target, windows=burn_windows, clock=clock,
            journal=journal, metrics=self.metrics,
        )
        self._lock = named_lock("telemetry.fleet")
        self._fleet: dict = {"ts": 0.0, "polls": 0}
        self._seen_events: set = set()
        self._last_tokens: Optional[Tuple[float, float]] = None
        self._scrapes = {"ok": 0, "error": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- scraping

    def discover_replicas(self) -> List[str]:
        urls = list(self.replica_urls)
        if self.router_url:
            try:
                stats = _get_json(
                    self.router_url + "/v1/stats", self.http_timeout
                )
                for u in (stats.get("replicas") or {}):
                    u = u.rstrip("/")
                    if u not in urls:
                        urls.append(u)
                self._scrapes["ok"] += 1
            except (urllib.error.URLError, OSError, ValueError,
                    json.JSONDecodeError) as e:
                self._scrapes["error"] += 1
                log.debug("router discovery failed: %s", e)
        return urls

    def _scrape_exposition(self, url: str) -> Optional[dict]:
        try:
            _, body = _http_get(url + "/metrics", self.http_timeout)
            self._scrapes["ok"] += 1
            return parse_exposition(body.decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            self._scrapes["error"] += 1
            log.debug("metrics scrape of %s failed: %s", url, e)
            return None

    def _scrape_json(self, url: str, path: str) -> Optional[dict]:
        try:
            out = _get_json(url + path, self.http_timeout)
            self._scrapes["ok"] += 1
            return out
        except (urllib.error.URLError, OSError, ValueError,
                json.JSONDecodeError) as e:
            self._scrapes["error"] += 1
            log.debug("scrape of %s%s failed: %s", url, path, e)
            return None

    def _ingest_events(self, events: List[dict]) -> None:
        for ev in events:
            key = (
                ev.get("seq"), round(float(ev.get("ts", 0.0)), 6),
                ev.get("component"), ev.get("reason"),
                ev.get("objectRef", ""),
            )
            if key in self._seen_events:
                continue
            self._seen_events.add(key)
            self.chip_hours.add_event(ev)
            self.stitcher.add_event(ev)

    # ---------------------------------------------------- federation

    def poll(self) -> dict:
        """One scrape→rollup→evaluate cycle (the periodic thread calls
        this; tests call it directly with a pinned clock)."""
        with get_tracer().span("telemetry.scrape"):
            return self._poll_inner()

    def _poll_inner(self) -> dict:
        now = self.clock()
        replicas = self.discover_replicas()
        per_replica: Dict[str, dict] = {}
        requests: Dict[str, float] = {}
        tokens = 0.0
        class_served: Dict[str, float] = {}
        class_missed: Dict[str, float] = {}
        kv_free = kv_total = 0.0
        profile_summaries: List[dict] = []
        profile_armed = 0

        for url in replicas:
            samples = self._scrape_exposition(url)
            stats = self._scrape_json(url, "/v1/stats")
            trace = self._scrape_json(url, "/v1/debug/trace?n=512")
            events = self._scrape_json(url, "/v1/debug/events?n=1000")
            profile = self._scrape_json(url, "/v1/debug/profile?n=1")
            alive = samples is not None or stats is not None
            per_replica[url] = {
                "ok": alive,
                **({"replica_id": stats.get("replica_id"),
                    "queued": stats.get("queued"),
                    "live_slots": stats.get("live_slots")}
                   if stats else {}),
            }
            if samples is not None:
                for (name, labels), v in samples.items():
                    if name == "tpuslice_serve_requests_total":
                        oc = dict(labels).get("outcome", "")
                        requests[oc] = requests.get(oc, 0.0) + v
                tokens += metric_sum(
                    samples, "tpuslice_serve_tokens_total"
                )
                for cls, v in metric_by_label(
                    samples, "tpuslice_serve_class_ttft_seconds_count",
                    "tenant_class",
                ).items():
                    class_served[cls] = class_served.get(cls, 0.0) + v
                for cls, v in metric_by_label(
                    samples, "tpuslice_serve_slo_missed_total",
                    "tenant_class", slo="ttft",
                ).items():
                    class_missed[cls] = class_missed.get(cls, 0.0) + v
            if stats is not None:
                kv = stats.get("kv") or {}
                free = float(kv.get("free") or 0)
                kv_free += free
                kv_total += free + float(kv.get("used") or 0)
            if trace is not None:
                self.stitcher.ingest_debug_payload(trace)
            if events is not None:
                self._ingest_events(events.get("events") or [])
            if profile is not None:
                if profile.get("armed"):
                    profile_armed += 1
                profile_summaries.append(profile.get("segments") or {})

        router_trace = router_events = None
        if self.router_url:
            router_trace = self._scrape_json(
                self.router_url, "/v1/debug/trace?n=512"
            )
            router_events = self._scrape_json(
                self.router_url, "/v1/debug/events?n=1000"
            )
        if router_trace is not None:
            self.stitcher.ingest_debug_payload(router_trace)
        if router_events is not None:
            self._ingest_events(router_events.get("events") or [])

        for url in self.probe_urls:
            trace = self._scrape_json(url, "/v1/debug/trace?n=512")
            events = self._scrape_json(url, "/v1/debug/events?n=1000")
            if trace is not None:
                self.stitcher.ingest_debug_payload(trace)
            if events is not None:
                self._ingest_events(events.get("events") or [])

        for path in self.trace_files:
            self.stitcher.ingest_file(path)
        for path in self.event_files:
            try:
                with open(path) as f:
                    evs = []
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            evs.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue
                    self._ingest_events(evs)
            except OSError as e:
                log.warning("cannot read event file %s: %s", path, e)

        # ---- rollups
        ok_requests = requests.get("ok", 0.0) \
            + requests.get("migrated", 0.0)
        goodput = 0.0
        if self._last_tokens is not None:
            t_prev, tok_prev = self._last_tokens
            dt = now - t_prev
            if dt > 0 and tokens >= tok_prev:
                goodput = (tokens - tok_prev) / dt
        self._last_tokens = (now, tokens)

        attainment: Dict[str, dict] = {}
        for cls in sorted(set(class_served) | set(class_missed)):
            served = class_served.get(cls, 0.0)
            missed = class_missed.get(cls, 0.0)
            att = 1.0 - (missed / served) if served > 0 else 1.0
            attainment[cls] = {
                "served": int(served),
                "missed": int(missed),
                "attainment": round(att, 6),
            }
            self.burn.observe(cls, missed, served)
            self.metrics.attainment.labels(tenant_class=cls).set(att)
        burn = self.burn.evaluate()

        chip_seconds = self.chip_hours.chip_seconds(now)
        chips_live = self.chip_hours.chips_live()
        chip_hours_per_mreq = 0.0
        if ok_requests > 0:
            chip_hours_per_mreq = (
                (chip_seconds / 3600.0) / (ok_requests / 1e6)
            )

        self.metrics.goodput.set(goodput)
        self.metrics.tokens.set(tokens)
        for oc, v in requests.items():
            self.metrics.requests.labels(outcome=oc).set(v)
        if kv_total > 0:
            self.metrics.kv_free_fraction.set(kv_free / kv_total)
        self.metrics.chip_seconds.set(chip_seconds)
        self.metrics.chips_live.set(chips_live)
        self.metrics.chip_hours_per_mreq.set(chip_hours_per_mreq)

        with self._lock:
            polls = self._fleet.get("polls", 0) + 1
        fleet = {
            "ts": round(now, 6),
            "polls": polls,
            "replicas": per_replica,
            "requests": {k: int(v) for k, v in sorted(
                requests.items()
            )},
            "ok_requests": int(ok_requests),
            "tokens": int(tokens),
            "goodput_tokens_per_sec": round(goodput, 2),
            "attainment": attainment,
            "slo_target": self.burn.target,
            "burn": burn,
            "kv": {
                "free": int(kv_free),
                "total": int(kv_total),
                "free_fraction": round(kv_free / kv_total, 4)
                if kv_total else 1.0,
            },
            "chip_hours": {
                "chip_seconds": round(chip_seconds, 3),
                "chips_live": chips_live,
                "chip_hours_per_million_requests": round(
                    chip_hours_per_mreq, 4
                ),
            },
            "traces": len(self.stitcher.trace_ids()),
            "scrapes": dict(self._scrapes),
            # fleet-merged profiler rollup: only replicas serving
            # GET /v1/debug/profile contribute; disarmed replicas
            # contribute empty summaries (armed_replicas says how many
            # actually record)
            "profile": {
                "armed_replicas": profile_armed,
                "segments": merge_profile_summaries(profile_summaries),
            },
        }
        with self._lock:
            self._fleet = fleet
        return fleet

    def fleet(self) -> dict:
        """The latest rollup snapshot (``GET /v1/fleet``)."""
        with self._lock:
            return dict(self._fleet)

    # ------------------------------------------------------ lifecycle

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
                self.metrics.scrapes.labels(outcome="ok").inc()
            except Exception:  # noqa: BLE001 - the loop must survive
                self.metrics.scrapes.labels(outcome="error").inc()
                log.warning("telemetry poll failed", exc_info=True)
            self._stop.wait(self.interval)

    def start(self) -> "FleetAggregator":
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-poll", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ------------------------------------------------------- HTTP plane


class _TelemetryHandler(BaseHTTPRequestHandler):
    aggregator: FleetAggregator = None  # type: ignore[assignment]

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        agg = type(self).aggregator
        qs = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query
        )
        if self.path.startswith("/healthz"):
            self._send(200, {"status": "ok"})
        elif self.path.startswith("/readyz"):
            fleet = agg.fleet()
            if fleet.get("polls", 0) > 0:
                self._send(200, {"status": "ok",
                                 "polls": fleet["polls"]})
            else:
                self._send(503, {"status": "no poll completed yet"})
        elif self.path.startswith("/v1/fleet/trace"):
            tid = (qs.get("trace_id") or [""])[0]
            if not tid:
                self._send(400, {"error": "trace_id is required"})
                return
            timeline = agg.stitcher.timeline(tid)
            if not timeline["spans"] and not timeline["linked"]:
                self._send(404, {"error": f"no spans collected for "
                                          f"trace {tid!r}"})
                return
            self._send(200, timeline)
        elif self.path.startswith("/v1/fleet"):
            self._send(200, agg.fleet())
        elif self.path.startswith("/metrics"):
            body = render(agg.metrics).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/v1/debug/trace"):
            try:
                self._send(200, debug_trace_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except LookupError as e:
                self._send(404, {"error": str(e)})
        elif self.path.startswith("/v1/debug/events"):
            try:
                self._send(200, debug_events_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
        elif self.path.startswith("/v1/debug/profile"):
            # debug parity with replicas/router/probes: the telemetry
            # process's OWN profiler ring (fleet rollup is /v1/fleet)
            try:
                self._send(200, debug_profile_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except LookupError as e:
                self._send(404, {"error": str(e)})
        else:
            self._send(404, {"error": f"no route {self.path}"})


class TelemetryServer:
    """The aggregator's HTTP plane: ``/v1/fleet``, ``/v1/fleet/trace``,
    ``/healthz``, ``/readyz``, ``/metrics``, ``/v1/debug/*``."""

    def __init__(self, aggregator: FleetAggregator,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.aggregator = aggregator
        handler = type("BoundTelemetryHandler", (_TelemetryHandler,),
                       {"aggregator": aggregator})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="telemetry-http",
            daemon=True,
        )

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "TelemetryServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)


# -------------------------------------------------------------- CLI


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpuslice-telemetry")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9102)
    ap.add_argument("--router", default=None,
                    help="router base URL (replica set is discovered "
                         "from its /v1/stats)")
    ap.add_argument("--replica", action="append", default=[],
                    help="replica base URL (repeatable; in addition "
                         "to router discovery)")
    ap.add_argument("--probe", action="append", default=[],
                    help="operator probe-server base URL (repeatable; "
                         "controller/agent planes)")
    ap.add_argument("--trace-file", action="append", default=[],
                    help="TPUSLICE_TRACE_FILE JSONL to ingest each "
                         "poll (repeatable)")
    ap.add_argument("--event-file", action="append", default=[],
                    help="TPUSLICE_EVENT_FILE JSONL to ingest each "
                         "poll (repeatable)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="scrape interval seconds")
    ap.add_argument("--slo-target", type=float, default=0.99,
                    help="attainment target the burn rate is "
                         "normalized against")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = build_parser().parse_args(argv)
    agg = FleetAggregator(
        router_url=args.router,
        replica_urls=tuple(args.replica),
        probe_urls=tuple(args.probe),
        trace_files=tuple(args.trace_file),
        event_files=tuple(args.event_file),
        interval=args.interval,
        slo_target=args.slo_target,
    ).start()
    srv = TelemetryServer(agg, host=args.host, port=args.port).start()
    log.info("fleet telemetry aggregator on %s (interval %gs)",
             srv.url, args.interval)
    forever = threading.Event()
    try:
        while not forever.is_set():
            forever.wait(60)
    except KeyboardInterrupt:
        pass
    finally:
        agg.stop()
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
