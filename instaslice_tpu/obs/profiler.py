"""Continuous performance profiler for the serving plane.

The fleet telemetry plane (obs/telemetry.py) answers *what* is slow —
goodput, SLO burn, chip-hours. This module answers **where the
milliseconds go**: every scheduler round decomposes into named segments
(admission / resume / preempt / prefill / dispatch / readback / host
bookkeeping) recorded as one bounded-ring :class:`RoundRecord`, and the
engine's dispatch seam emits timeline events (dispatch start, readback
landing, mid-traffic jit compiles) into a second ring. Three export
surfaces share the rings:

- ``GET /v1/debug/profile`` — armed state, per-segment p50/p95
  summaries, the most recent round records and timeline events
  (:func:`debug_profile_payload`, shared by the serving api_server,
  the router, the operator probes, and the telemetry server so the
  debug surface cannot drift between planes).
- **Chrome trace-event JSON** (:func:`chrome_trace`) — the round
  records, timeline events, and ``utils/trace.py`` spans interleaved
  onto one timeline (one pid per component, one tid per lane),
  openable in Perfetto / ``chrome://tracing``. The CLI drives it:
  ``tpuslice profile --url ... --out trace.json``.
- **Per-request latency waterfall** (:func:`waterfall_payload`) —
  queue → admission → prefill → decode/spec rounds → (preempt / park /
  resume) → finish, stitched from round records + journal events +
  trace spans by rid / trace id (``tpuslice waterfall <rid>`` or
  ``GET /v1/debug/profile?rid=...``).

Arming: ``TPUSLICE_PROFILE=1`` in the environment, ``--profile`` on
``tpuslice-serve``, or :meth:`Profiler.arm`. Disarmed, the hot path is
a single attribute check and a shared no-op timer (the scheduler's
``with pt.seg(...)`` blocks enter a reusable ``nullcontext``) — cheap
enough to leave compiled in everywhere. Armed, a round costs two
monotonic clock reads per segment plus one deque append; the
``profile-smoke`` gate asserts the armed serving path keeps >= 95%
of the unprofiled arm's tok/s. Knobs: ``TPUSLICE_PROFILE`` (arm),
``TPUSLICE_PROFILE_RING`` (ring capacity, default 4096),
``TPUSLICE_COMPILE_GRACE`` (seconds of traffic during which compile
deltas re-baseline silently — lazily-compiled first-dispatch programs
are startup, not the mid-run compile bug CompileObserved announces).

Compile attribution: :class:`CompileWatch` snapshots the engine's
per-jit compile-cache sizes (``engine.compiled_programs()``) and the
process-wide compile wall-clock accumulator (a ``jax.monitoring``
duration listener, when the running jax exposes one). Any cache growth
observed after the traffic grace window is a **mid-traffic compile**
— the scheduler journals it as ``CompileObserved`` with the program
name, the dispatch shape key, and the accumulated compile wall ms, so
the "cold mid-run compile polluted p95" class of bug self-announces
instead of requiring archaeology (docs/OBSERVABILITY.md "Profiling").
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from instaslice_tpu.api.constants import (
    REASON_DRAINED,
    REASON_PREEMPTED,
    REASON_RESUMED,
    REASON_SESSION_EXPORTED,
    REASON_SHED,
)
from instaslice_tpu.utils.lockcheck import named_lock
from instaslice_tpu.utils.trace import get_tracer, summarize_durations

#: the round-record segment vocabulary (docs/OBSERVABILITY.md
#: "Profiling" documents each): everything a scheduler round spends
#: time on lands in exactly one of these.
SEGMENTS = (
    "admission",   # admission pass: ordering, cost model, burst build
    "resume",      # un-parking preempted requests into freed slots
    "preempt",     # SLO preemption + block-pressure relief
    "prefill",     # engine prefill dispatch inside an admission
    "dispatch",    # decode/spec dispatch (host->device enqueue)
    "readback",    # blocking on the device->host token copy
    "host",        # everything else: pumps, sweeps, delivery, gauges
)


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "1", "true", "yes", "on"
    )


# ------------------------------------------------- compile wall clock

#: process-wide compile wall-ms accumulator, fed by a jax.monitoring
#: duration listener (absent/changed jax internals degrade to a zero
#: accumulator — attribution loses wall ms, never correctness)
_compile_lock = named_lock("profile.compile")
_compile_ms = 0.0
_listener_installed = False


def _on_jax_event(event, duration, **_kw) -> None:
    global _compile_ms
    try:
        if "compil" in str(event):
            with _compile_lock:
                _compile_ms += float(duration) * 1e3
    except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
        pass           # monitoring must never break a dispatch


def install_compile_listener() -> None:
    """Register the jax.monitoring duration listener (idempotent).
    Called by :class:`CompileWatch`; safe on a jax without the
    monitoring module (the accumulator just stays zero)."""
    global _listener_installed
    with _compile_lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_jax_event)
    except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
        pass


def compile_wall_ms() -> float:
    """Total jit-compile wall ms this process has spent (0.0 when the
    running jax exposes no monitoring seam)."""
    with _compile_lock:
        return _compile_ms


class CompileWatch:
    """Detect jit compiles that land outside the warm window.

    Snapshot ``engine.compiled_programs()`` at construction (the warm_*
    window: warm_prefill_buckets / warm_spec_programs and everything
    else that compiles before traffic). :meth:`mark_traffic` re-baselines
    at the first admission; :meth:`check` then reports any cache growth
    as mid-traffic compiles — except inside the ``grace`` window after
    traffic starts, where growth re-baselines silently (first-dispatch
    lazy compiles are startup cost, not the mid-run bug)."""

    def __init__(self, engine, grace: Optional[float] = None) -> None:
        self._engine = engine
        if grace is None:
            grace = float(os.environ.get(
                "TPUSLICE_COMPILE_GRACE", "5.0") or 5.0)
        self.grace = grace
        self.in_traffic = False
        self._traffic_t0 = 0.0
        self._counts = self._snapshot()
        self._wall = compile_wall_ms()
        install_compile_listener()

    def _snapshot(self) -> Dict[str, int]:
        try:
            return dict(self._engine.compiled_programs())
        except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
            return {}

    def mark_traffic(self) -> None:
        """First admission: the warm window is over. Everything
        compiled so far belongs to it; re-baseline."""
        if not self.in_traffic:
            self.in_traffic = True
            self._traffic_t0 = time.monotonic()
            self._counts = self._snapshot()
            self._wall = compile_wall_ms()

    def check(self) -> List[dict]:
        """Compile-cache growth since the last check (after traffic
        started and past the grace window). Each entry:
        ``{"program", "count", "wall_ms"}``."""
        if not self.in_traffic:
            return []
        now = self._snapshot()
        if now == self._counts:
            return []
        wall = compile_wall_ms()
        out: List[dict] = []
        if time.monotonic() - self._traffic_t0 >= self.grace:
            for prog, n in sorted(now.items()):
                prev = self._counts.get(prog, 0)
                if n > prev:
                    out.append({
                        "program": prog,
                        "count": n - prev,
                        "wall_ms": round(max(0.0, wall - self._wall), 3),
                    })
        self._counts = now
        self._wall = wall
        return out


# ------------------------------------------------------- round timing


class RoundTimer:
    """Accumulates one scheduler round's segment timeline. Created via
    :meth:`Profiler.round_timer`; the scheduler wraps each phase in
    ``with pt.seg(name):`` and hands the timer back through
    :meth:`Profiler.finish_round`. All clocks are ``time.monotonic()``
    so engine-side landing stamps (``last_dispatch_landed``) can be
    spliced in via :meth:`add` without epoch mixing."""

    __slots__ = ("t0", "wall0", "segs", "meta", "_open")

    def __init__(self) -> None:
        self.t0 = time.monotonic()
        self.wall0 = time.time()
        self.segs: List[Tuple[str, float, float]] = []
        self.meta: Dict[str, object] = {}
        #: open-segment stack: [start, nested_elapsed_s] frames. segs
        #: may nest (prefill inside the admission pass); each instant
        #: must land in exactly ONE segment, so an enclosing segment
        #: records its wall MINUS everything nested inside it — that
        #: keeps sum(segs) <= round wall, the ledger invariant the
        #: reconciliation tests assert.
        self._open: List[List[float]] = []

    @contextlib.contextmanager
    def seg(self, name: str):
        s = time.monotonic()
        frame = [s, 0.0]
        self._open.append(frame)
        try:
            yield
        finally:
            e = time.monotonic()
            self._open.pop()
            if self._open:
                self._open[-1][1] += e - s
            dur = (e - s) - frame[1]
            if dur > 0:
                self.segs.append((
                    name,
                    round((s - self.t0) * 1e3, 3),
                    round(dur * 1e3, 3),
                ))

    def add(self, name: str, start: float, dur_s: float) -> None:
        """Record an externally-measured segment (``start`` is a
        ``time.monotonic()`` stamp, ``dur_s`` seconds)."""
        if dur_s <= 0:
            return
        self.segs.append((
            name,
            round((start - self.t0) * 1e3, 3),
            round(dur_s * 1e3, 3),
        ))

    def note(self, **meta) -> None:
        self.meta.update(meta)

    def bump(self, key: str, n: int = 1) -> None:
        self.meta[key] = int(self.meta.get(key, 0)) + n


class _NoopRoundTimer:
    """Shared disarmed timer: every method is a constant-time no-op
    and ``seg`` hands back one reusable nullcontext."""

    __slots__ = ()
    _null = contextlib.nullcontext()

    def seg(self, name: str):
        return self._null

    def add(self, name: str, start: float, dur_s: float) -> None:
        pass

    def note(self, **meta) -> None:
        pass

    def bump(self, key: str, n: int = 1) -> None:
        pass


NOOP_TIMER = _NoopRoundTimer()


@dataclasses.dataclass
class RoundRecord:
    """One scheduler round's anatomy: wall time, per-segment timeline
    (name, start offset ms, duration ms), and the round metadata the
    scheduler noted (phase, batch, n_steps, k, rids, trace ids,
    admitted/resumed/preempted counts, blocks free)."""

    idx: int                 # profiler-wide monotonic round number
    ts: float                # unix seconds at round start
    wall_ms: float
    phase: str               # "decode" | "spec"
    segs: Tuple[Tuple[str, float, float], ...]
    meta: Dict[str, object]

    def seg_totals(self) -> Dict[str, float]:
        """Per-segment summed ms (a segment name can appear several
        times in one round — e.g. split host work)."""
        out: Dict[str, float] = {}
        for name, _start, dur in self.segs:
            out[name] = round(out.get(name, 0.0) + dur, 3)
        return out

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "ts": round(self.ts, 6),
            "wallMs": round(self.wall_ms, 3),
            "phase": self.phase,
            "segs": [[n, s, d] for n, s, d in self.segs],
            "meta": dict(self.meta),
        }


# ------------------------------------------------------------ profiler


class Profiler:
    """Bounded rings of round records and timeline events + an armed
    flag. One per process by default (:func:`get_profiler`), created
    armed when ``TPUSLICE_PROFILE`` is set."""

    def __init__(self, capacity: Optional[int] = None,
                 armed: Optional[bool] = None) -> None:
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "TPUSLICE_PROFILE_RING", "4096") or 4096)
            except ValueError:
                capacity = 4096
        capacity = max(16, capacity)
        self._lock = named_lock("profile.ring")
        self._rounds: deque = deque(maxlen=capacity)
        self._events: deque = deque(maxlen=capacity)
        self.rounds_recorded = 0
        self.events_recorded = 0
        if armed is None:
            armed = _env_flag("TPUSLICE_PROFILE")
        #: plain bool read on the hot path (GIL-atomic); flipped by
        #: arm()/disarm() — mid-flight timers of the old state record
        #: or drop harmlessly
        self.armed = bool(armed)

    def arm(self) -> None:
        self.armed = True

    def disarm(self) -> None:
        self.armed = False

    # -------------------------------------------------------- recording

    def round_timer(self):
        """A fresh :class:`RoundTimer` when armed, the shared no-op
        otherwise — the scheduler never branches on armed itself."""
        return RoundTimer() if self.armed else NOOP_TIMER

    def finish_round(self, timer, phase: str = "",
                     **meta) -> Optional[RoundRecord]:
        """Close a round timer into a ring record. No-op (returns
        None) for the disarmed shared timer."""
        if timer is NOOP_TIMER or not isinstance(timer, RoundTimer):
            return None
        wall_ms = (time.monotonic() - timer.t0) * 1e3
        m = dict(timer.meta)
        m.update(meta)
        with self._lock:
            self.rounds_recorded += 1
            rec = RoundRecord(
                idx=self.rounds_recorded, ts=timer.wall0,
                wall_ms=round(wall_ms, 3), phase=str(phase),
                segs=tuple(timer.segs), meta=m,
            )
            self._rounds.append(rec)
        return rec

    def event(self, kind: str, name: str, dur_ms: float = 0.0,
              ts: Optional[float] = None, **attrs) -> None:
        """Append one timeline event (dispatch / readback / compile /
        proxy / migrate lanes). Constant-time no-op while disarmed."""
        if not self.armed:
            return
        ev = {
            "ts": round(time.time() if ts is None else ts, 6),
            "kind": str(kind),
            "name": str(name),
            "durMs": round(float(dur_ms), 3),
            "attrs": {k: str(v) for k, v in attrs.items()},
        }
        with self._lock:
            self.events_recorded += 1
            self._events.append(ev)

    # --------------------------------------------------------- querying

    def rounds(self, n: Optional[int] = None) -> List[RoundRecord]:
        with self._lock:
            out = list(self._rounds)
        return out[-n:] if n else out

    def events(self, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        return out[-n:] if n else out

    def segment_summary(self) -> Dict[str, dict]:
        """Per-segment count/p50/p95/max over the ring's round records
        (per-round summed ms per segment), plus a ``round`` row for
        whole-round wall time — the ``GET /v1/debug/profile`` summary
        and the bench's per-arm profile artifact."""
        by: Dict[str, List[float]] = {}
        for rec in self.rounds():
            for name, dur in rec.seg_totals().items():
                by.setdefault(name, []).append(dur)
            by.setdefault("round", []).append(rec.wall_ms)
        return summarize_durations(by)

    def clear(self) -> None:
        with self._lock:
            self._rounds.clear()
            self._events.clear()


_default: Optional[Profiler] = None
_default_lock = named_lock("profile.default")


def get_profiler() -> Profiler:
    """Process-wide default profiler (created lazily; armed iff
    ``TPUSLICE_PROFILE`` was set at creation or ``arm()`` was called)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Profiler()
        return _default


def reset_profiler(profiler: Optional[Profiler] = None) -> None:
    """Swap the process-wide default (test isolation — mirrors
    ``reset_tracer``/``reset_journal``)."""
    global _default
    with _default_lock:
        _default = profiler


# ---------------------------------------------------- debug endpoint


def debug_profile_payload(qs: Dict[str, list],
                          profiler: Optional[Profiler] = None,
                          tracer=None, journal=None) -> dict:
    """Build the ``GET /v1/debug/profile`` response from parsed
    query-string lists — shared by the serving api_server, the router,
    the operator probes, and the telemetry server. Default mode:
    armed state, per-segment summaries, and the ``n`` most recent
    round records / timeline events (default 20, bounded by the ring).
    ``?rid=X`` switches to the per-request waterfall (X is an engine
    rid or a trace id). Raises :class:`ValueError` on a malformed
    ``n`` (callers map to HTTP 400) and :class:`LookupError` when a
    requested rid has no recorded state (HTTP 404)."""
    p = profiler if profiler is not None else get_profiler()
    try:
        n = int((qs.get("n") or ["20"])[0])
        if n < 1:
            raise ValueError
    except ValueError:
        raise ValueError("n must be a positive integer") from None
    rid = (qs.get("rid") or [""])[0]
    if rid:
        return waterfall_payload(rid, profiler=p, tracer=tracer,
                                 journal=journal)
    return {
        "armed": p.armed,
        "rounds": p.rounds_recorded,
        "events": p.events_recorded,
        "compileWallMs": round(compile_wall_ms(), 3),
        "segments": p.segment_summary(),
        "recent": [r.to_dict() for r in p.rounds(n)],
        "recentEvents": p.events(n),
        "compiles": p.events(n, kind="compile"),
    }


# ------------------------------------------------- chrome trace export


def chrome_trace(rounds: Optional[List[dict]] = None,
                 events: Optional[List[dict]] = None,
                 spans: Optional[List[dict]] = None) -> dict:
    """Interleave round records, timeline events, and tracer spans into
    Chrome trace-event JSON ({"traceEvents": [...]}) — loadable in
    Perfetto / ``chrome://tracing``. Inputs are payload-shaped dicts
    (``RoundRecord.to_dict`` / profiler event / ``Span.to_dict``) so
    the CLI can build a trace from HTTP payloads without touching the
    live rings. One pid per component (scheduler / engine / each span
    name prefix), one tid per lane (rounds, segments, event kind,
    per-slot span lanes); ``ts``/``dur`` are microseconds from the
    earliest input timestamp."""
    rounds = rounds or []
    events = events or []
    spans = spans or []
    out: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[int, str], int] = {}

    def pid(name: str) -> int:
        if name not in pids:
            pids[name] = len(pids) + 1
            out.append({"ph": "M", "name": "process_name", "ts": 0,
                        "pid": pids[name],
                        "args": {"name": name}})
        return pids[name]

    def tid(p: int, name: str) -> int:
        key = (p, name)
        if key not in tids:
            tids[key] = sum(1 for k in tids if k[0] == p) + 1
            out.append({"ph": "M", "name": "thread_name", "ts": 0,
                        "pid": p, "tid": tids[key],
                        "args": {"name": name}})
        return tids[key]

    starts = (
        [float(r.get("ts") or 0.0) for r in rounds]
        + [float(e.get("ts") or 0.0) for e in events]
        + [float(s.get("start") or 0.0) for s in spans]
    )
    base = min([s for s in starts if s > 0], default=0.0)

    def us(t: float) -> float:
        return round(max(0.0, (t - base)) * 1e6, 1)

    for r in rounds:
        p = pid("scheduler")
        t0 = us(float(r.get("ts") or base))
        meta = {k: str(v) for k, v in (r.get("meta") or {}).items()}
        out.append({
            "ph": "X", "cat": "round",
            "name": "round/%s" % (r.get("phase") or "decode"),
            "pid": p, "tid": tid(p, "rounds"), "ts": t0,
            "dur": round(float(r.get("wallMs") or 0.0) * 1e3, 1),
            "args": dict(meta, idx=str(r.get("idx", ""))),
        })
        seg_tid = tid(p, "segments")
        for seg in (r.get("segs") or []):
            name, start_ms, dur_ms = seg[0], float(seg[1]), float(seg[2])
            out.append({
                "ph": "X", "cat": "segment", "name": name,
                "pid": p, "tid": seg_tid,
                "ts": round(t0 + start_ms * 1e3, 1),
                "dur": round(dur_ms * 1e3, 1),
            })
    for e in events:
        p = pid("engine")
        t = tid(p, str(e.get("kind") or "event"))
        dur_ms = float(e.get("durMs") or 0.0)
        ev = {
            "cat": str(e.get("kind") or "event"),
            "name": str(e.get("name") or ""),
            "pid": p, "tid": t,
            "args": dict(e.get("attrs") or {}),
        }
        if dur_ms > 0:
            # the event is stamped at its END: shift back by dur
            ev["ph"] = "X"
            ev["dur"] = round(dur_ms * 1e3, 1)
            ev["ts"] = round(
                max(0.0, us(float(e.get("ts") or base))
                    - dur_ms * 1e3), 1)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
            ev["ts"] = us(float(e.get("ts") or base))
        out.append(ev)
    for s in spans:
        name = str(s.get("name") or "span")
        comp = name.split(".", 1)[0] or "span"
        p = pid(comp)
        attrs = dict(s.get("attrs") or {})
        lane = attrs.get("slot")
        t = tid(p, "slot:%s" % lane if lane is not None else "spans")
        for key in ("traceId", "spanId", "parentId"):
            if s.get(key):
                attrs[key] = s[key]
        out.append({
            "ph": "X", "cat": "span", "name": name,
            "pid": p, "tid": t,
            "ts": us(float(s.get("start") or base)),
            "dur": round(float(s.get("durationMs") or 0.0) * 1e3, 1),
            "args": attrs,
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ------------------------------------------------------- waterfall


#: journal reason → the outcome a terminal event implies when the root
#: ``serve.request`` span is missing (shed before any span recorded)
_TERMINAL_OUTCOMES = {
    REASON_SHED: "shed",
    REASON_DRAINED: "drained",
    REASON_SESSION_EXPORTED: "migrated",
}

#: span name → waterfall stage label ("serve.decode_round" resolves
#: per-span from its phase attr)
_STAGE_NAMES = {
    "serve.queue": "queue",
    "serve.prefill": "prefill",
    "serve.preempt": "preempt",
    "serve.resume": "resume",
}


def waterfall_payload(rid, profiler: Optional[Profiler] = None,
                      tracer=None, journal=None) -> dict:
    """Stitch one request's latency waterfall from round records,
    journal events, and trace spans. ``rid`` is an engine rid (mapped
    to its trace id through the round records' rid/trace-id pairing)
    or a trace id directly. Raises :class:`LookupError` when nothing
    recorded mentions the request."""
    p = profiler if profiler is not None else get_profiler()
    t = tracer if tracer is not None else get_tracer()
    j = journal
    if j is None:
        from instaslice_tpu.obs.journal import get_journal

        j = get_journal()
    key = str(rid)
    trace_id = ""
    if key.isdigit():
        want = int(key)
        for rec in reversed(p.rounds()):
            rids = list(rec.meta.get("rids") or ())
            tis = list(rec.meta.get("trace_ids") or ())
            if want in rids:
                i = rids.index(want)
                if i < len(tis) and tis[i]:
                    trace_id = str(tis[i])
                break
    if not trace_id:
        trace_id = key
    spans = t.trace(trace_id)
    evs = j.events(trace_id=trace_id)
    recs = [rec for rec in p.rounds()
            if trace_id in [str(x) for x in
                            (rec.meta.get("trace_ids") or ())]]
    if not spans and not evs and not recs:
        raise LookupError(
            "nothing recorded for request %r (not an engine rid in "
            "the round ring, not a trace id with spans or journal "
            "events)" % key
        )
    starts = ([s.start for s in spans] + [e.ts for e in evs]
              + [rec.ts for rec in recs])
    t0 = min(starts)
    root = None
    stages: List[dict] = []
    for s in sorted(spans, key=lambda x: x.start):
        if s.name == "serve.request":
            root = s
            continue
        if s.name == "serve.decode_round":
            stage = "%s round" % s.attrs.get("phase", "decode")
        elif s.name == "serve.migrate":
            stage = "migrate-%s" % s.attrs.get("direction", "out")
        else:
            stage = _STAGE_NAMES.get(s.name, s.name)
        stages.append({
            "stage": stage,
            "span": s.name,
            "startMs": round((s.start - t0) * 1e3, 3),
            "durationMs": round(s.duration_ms, 3),
            "attrs": dict(s.attrs),
        })
    markers = [{
        "atMs": round((e.ts - t0) * 1e3, 3),
        "reason": e.reason,
        "message": e.message,
    } for e in sorted(evs, key=lambda e: e.ts)]
    outcome = ""
    if root is not None:
        outcome = root.attrs.get("outcome", "")
    if not outcome:
        for e in evs:
            if e.reason in _TERMINAL_OUTCOMES:
                outcome = _TERMINAL_OUTCOMES[e.reason]
    preemptions = sum(1 for s in stages if s["stage"] == "preempt")
    if preemptions and outcome == "ok":
        outcome = "preempted-resumed"
    total_ms = (round(root.duration_ms, 3) if root is not None else
                round(max(
                    [s["startMs"] + s["durationMs"] for s in stages]
                    + [m["atMs"] for m in markers] + [0.0]
                ), 3))
    return {
        "rid": key,
        "traceId": trace_id,
        "outcome": outcome,
        "totalMs": total_ms,
        "preemptions": preemptions,
        "stages": stages,
        "markers": markers,
        "rounds": [rec.to_dict() for rec in recs],
    }
