"""Deterministic, seedable fault injection across the whole stack.

The chaos tiers already hammer the control plane (``tests/test_chaos.py``
drives the fake backend's ``inject_failures``/``fail_chip``), but those
hooks are backend-local and hand-rolled per test. This module is the one
fault surface for everything else: a :class:`FaultPlan` holds a seeded
RNG plus per-**site** specs (probability, exact call schedules, fire
caps), and adapters graft it onto each layer —

- :class:`FaultyKubeClient` wraps any :class:`KubeClient` and injects
  transient API failures (503/429/connection reset) into the verbs and
  mid-stream disconnects into watches — what a flaky API server or an
  overloaded kube-apiserver does to the control plane.
- :class:`FaultyBackend` wraps a :class:`DeviceBackend` and injects
  :class:`DeviceError`, slow dispatch, and chip failures.
- :func:`engine_fault_hook` returns the callable a
  :class:`~instaslice_tpu.serving.engine.ServingEngine` consults before
  every dispatch (``engine.fault_hook``): it can delay (slow dispatch),
  raise (transient backend error), or **poison** the donated KV cache
  exactly the way a failed jitted call does — driving the engine's
  recovery path for real.
- The API scheduler consults a plan-provided hook once per loop round
  (site ``scheduler.round``) for delays/errors in the serving loop.

Everything is deterministic given the seed: the same plan replays the
same fault sequence (per-site call counters, one shared RNG). Plans are
built in tests or parsed from the ``TPUSLICE_FAULT_PLAN`` env var, which
:class:`~instaslice_tpu.sim.SimCluster` honors so any sim-driven tier
can run under faults without code changes::

    TPUSLICE_FAULT_PLAN="seed=7;kube.request:p=0.05,kinds=http-503|conn-reset;device.reserve:p=0.1"

Grammar: ``seed=N`` then ``;``-separated ``site:key=val,key=val`` specs
with keys ``p`` (probability), ``kinds`` (``|``-separated), ``at``
(``|``-separated exact call numbers, 1-based), ``max`` (fire cap),
``delay`` (seconds, for kind ``delay``).

Separately from the live-process faults above, **crash points** model a
process dying mid-lifecycle (docs/RECOVERY.md): ``TPUSLICE_CRASH_AT=
"<site>[:nth][,...]"`` names code sites that hard-stop the component the
``nth`` time they are reached (default: first). Components consult
:func:`maybe_crash` at their write-sequence edges — controller
mid-``_write_allocation`` / mid-ungate, agent mid-realize /
mid-teardown, repacker between drain and re-grant, serving scheduler
mid-session-export. In-process (the sim / chaos tiers) a fired crash
point raises :class:`InjectedCrash` — a ``BaseException`` so every
``except Exception`` keep-alive guard lets it through exactly like a
SIGKILL — and the component's driver restarts a fresh instance against
the durable state (``SimCluster.restart_controller()`` /
``restart_agent()``). With ``TPUSLICE_CRASH_HARD=1`` the process
``os._exit(17)``s instead, for real multi-process kill testing.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from instaslice_tpu.kube.client import ApiError, KubeClient, WatchEvent
from instaslice_tpu.utils.lockcheck import named_lock

# Network nemesis layer (partitions, latency, watch dup/reorder,
# throttling — docs/RECOVERY.md "Partitions & gray failures") lives in
# ``faults/netchaos.py``; this module stays the one fault facade.
from instaslice_tpu.faults.netchaos import (  # noqa: F401  (re-exports)
    NemesisKubeClient,
    NemesisPlan,
    NemesisRule,
    PartitionError,
    get_nemesis,
    reset_nemesis,
    set_nemesis,
)


class FaultError(Exception):
    """An injected failure (distinguishable from organic ones in logs)."""


class InjectedCrash(BaseException):
    """A simulated process death at a named crash point.

    Deliberately derives :class:`BaseException`: the reconcile
    framework, the repacker tick, and the serving scheduler all wrap
    their loops in ``except Exception`` keep-alive guards, and a crash
    must kill the component *through* those guards the way a SIGKILL
    would — anything that absorbs it is a bug the chaos tier exists to
    catch."""

    def __init__(self, site: str) -> None:
        super().__init__(f"injected crash at {site}")
        self.site = site


class CrashPlan:
    """Deterministic process-death schedule over named crash sites.

    ``sites`` maps site name → 1-based call number at which to fire
    (each site fires at most once — a crashed component does not keep
    crashing; its *restart* re-arms nothing). Thread-safe like
    :class:`FaultPlan`: crash sites sit on controller workers, agent
    reconcilers, and the serving scheduler concurrently."""

    def __init__(self, sites: Optional[Dict[str, int]] = None,
                 hard: bool = False) -> None:
        self.sites: Dict[str, int] = dict(sites or {})
        self.hard = hard
        self.calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self._lock = named_lock("faults.crashplan")

    def arm(self, site: str, nth: int = 1) -> "CrashPlan":
        """Register/replace a crash site; returns self for chaining.
        ``nth`` counts from THIS arming: re-arming resets the site's
        call counter (otherwise a kill-loop re-arming a hot site after
        its calls already passed ``nth`` could silently never fire)."""
        with self._lock:
            self.sites[site] = max(1, int(nth))
            self.fired.pop(site, None)
            self.calls.pop(site, None)
        return self

    def check(self, site: str) -> None:
        """One call at ``site``: raises :class:`InjectedCrash` (or
        hard-exits) when the armed call number is reached."""
        with self._lock:
            self.calls[site] = n = self.calls.get(site, 0) + 1
            nth = self.sites.get(site)
            if nth is None or site in self.fired or n != nth:
                return
            self.fired[site] = n
        if self.hard or os.environ.get("TPUSLICE_CRASH_HARD") == "1":
            os._exit(17)
        raise InjectedCrash(site)

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                name: {"calls": self.calls.get(name, 0),
                       "fired": self.fired.get(name, 0)}
                for name in set(self.calls) | set(self.sites)
            }

    @classmethod
    def from_env(cls, text: Optional[str] = None) -> Optional["CrashPlan"]:
        """Parse ``TPUSLICE_CRASH_AT`` (``site[:nth]`` comma-separated).
        Returns None for empty/missing text."""
        if text is None:
            text = os.environ.get("TPUSLICE_CRASH_AT", "")
        text = (text or "").strip()
        if not text:
            return None
        plan = cls()
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            site, _, nth = part.partition(":")
            try:
                n = int(nth) if nth else 1
            except ValueError:
                # fail FAST and fail CLEAR: this parses at import time
                # in every component, and a chaos knob that silently
                # no-oped would invalidate the whole chaos run — but
                # the operator must see the misconfigured variable,
                # not an int() traceback deep in an import cascade
                raise ValueError(
                    f"TPUSLICE_CRASH_AT: malformed entry {part!r} "
                    f"(want site[:nth] with integer nth, e.g. "
                    f"'agent.realize:2')"
                ) from None
            plan.arm(site.strip(), n)
        return plan


#: the process-default crash plan consulted by :func:`maybe_crash` —
#: None (the overwhelmingly common case) costs one global read per
#: crash-point visit
_crash_plan: Optional[CrashPlan] = CrashPlan.from_env()


def set_crash_plan(plan: Optional[CrashPlan]) -> None:
    """Install the process crash plan (tests / the sim chaos driver)."""
    global _crash_plan
    _crash_plan = plan


def get_crash_plan() -> Optional[CrashPlan]:
    return _crash_plan


def reset_crash_plan() -> None:
    """Re-read ``TPUSLICE_CRASH_AT`` (test isolation)."""
    global _crash_plan
    _crash_plan = CrashPlan.from_env()


def maybe_crash(site: str) -> None:
    """THE crash-point hook: components call this at lifecycle edges
    (docs/RECOVERY.md catalogs the sites); a no-op unless a plan armed
    the site."""
    plan = _crash_plan
    if plan is not None:
        plan.check(site)


class InjectedApiError(ApiError):
    """An injected kube API failure; ``code`` carries the HTTP status."""


@dataclass
class SiteSpec:
    """How one site misbehaves. ``kinds`` is sampled uniformly when the
    site fires; ``at_calls`` (1-based call numbers) always fire
    regardless of probability — exact schedules for regression tests."""

    probability: float = 0.0
    kinds: Tuple[str, ...] = ("error",)
    at_calls: frozenset = field(default_factory=frozenset)
    max_fires: int = -1          # -1 = unlimited
    delay_s: float = 0.01


class FaultPlan:
    """Seeded fault schedule over named sites. Thread-safe: the serving
    data plane consults it from the scheduler thread while HTTP threads
    and the control plane consult it concurrently."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self.sites: Dict[str, SiteSpec] = {}
        self.calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self._lock = named_lock("faults.plan")

    def site(self, name: str, probability: float = 0.0,
             kinds: Tuple[str, ...] = ("error",), at_calls=(),
             max_fires: int = -1, delay_s: float = 0.01) -> "FaultPlan":
        """Register/replace a site spec; returns self for chaining."""
        self.sites[name] = SiteSpec(
            probability=probability, kinds=tuple(kinds),
            at_calls=frozenset(at_calls), max_fires=max_fires,
            delay_s=delay_s,
        )
        return self

    def fire(self, name: str) -> Optional[str]:
        """One call at ``name``: returns the fault kind to inject, or
        None. Counts every call (fired or not) so ``at_calls`` schedules
        are exact."""
        with self._lock:
            spec = self.sites.get(name)
            self.calls[name] = n = self.calls.get(name, 0) + 1
            if spec is None:
                return None
            if 0 <= spec.max_fires <= self.fired.get(name, 0):
                return None
            hit = n in spec.at_calls or (
                spec.probability > 0
                and self.rng.random() < spec.probability
            )
            if not hit:
                return None
            self.fired[name] = self.fired.get(name, 0) + 1
            return (spec.kinds[self.rng.randrange(len(spec.kinds))]
                    if len(spec.kinds) > 1 else spec.kinds[0])

    def randrange(self, n: int) -> int:
        """A draw from the plan's RNG under its lock — wrappers that
        need extra randomness (e.g. which chip to fail) must come
        through here, or concurrent fire() calls would interleave with
        the draw and break seeded replayability."""
        with self._lock:
            return self.rng.randrange(n)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-site {calls, fired} — chaos tests log this on failure so
        a regression names the fault sequence that broke it."""
        with self._lock:
            return {
                name: {"calls": self.calls.get(name, 0),
                       "fired": self.fired.get(name, 0)}
                for name in set(self.calls) | set(self.sites)
            }

    # ------------------------------------------------------------- env

    @classmethod
    def from_env(cls, text: Optional[str] = None) -> Optional["FaultPlan"]:
        """Parse the ``TPUSLICE_FAULT_PLAN`` grammar (module docstring).
        Returns None for empty/missing text so callers can write
        ``plan = FaultPlan.from_env()`` unconditionally."""
        if text is None:
            import os

            text = os.environ.get("TPUSLICE_FAULT_PLAN", "")
        text = (text or "").strip()
        if not text:
            return None
        seed = 0
        specs: List[Tuple[str, dict]] = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[len("seed="):])
                continue
            site, _, body = part.partition(":")
            kw: dict = {}
            for item in body.split(","):
                if not item.strip():
                    continue
                key, _, val = item.partition("=")
                key = key.strip()
                if key == "p":
                    kw["probability"] = float(val)
                elif key == "kinds":
                    kw["kinds"] = tuple(val.split("|"))
                elif key == "at":
                    kw["at_calls"] = frozenset(
                        int(x) for x in val.split("|") if x
                    )
                elif key == "max":
                    kw["max_fires"] = int(val)
                elif key == "delay":
                    kw["delay_s"] = float(val)
                else:
                    raise ValueError(
                        f"TPUSLICE_FAULT_PLAN: unknown key {key!r} "
                        f"in {part!r}"
                    )
            specs.append((site.strip(), kw))
        plan = cls(seed)
        for site, kw in specs:
            plan.site(site, **kw)
        return plan


# --------------------------------------------------------------- kube

class FaultyKubeClient(KubeClient):
    """Injects API flakiness between a consumer and any
    :class:`KubeClient`. Sites:

    - ``kube.request`` — every verb. Kinds: ``http-503``/``http-500``
      (InjectedApiError with that code), ``http-429`` (too many
      requests), ``conn-reset`` (ConnectionResetError — what a dropped
      TCP session surfaces after the real client's retries give up),
      ``delay`` (slow API server).
    - ``kube.watch`` — consulted per watch **event**. Kind
      ``disconnect`` truncates the stream mid-flight (the consumer must
      re-establish and resume); ``delay`` stalls delivery.

    The wrapper injects at the KubeClient interface, so it composes
    with both the in-process fake and :class:`RealKubeClient` (where it
    models failures that survive the client's own retry layer)."""

    def __init__(self, inner: KubeClient, plan: FaultPlan) -> None:
        self.inner = inner
        self.plan = plan
        # forward the watch-pacing hint so wrapped Managers behave
        pref = getattr(inner, "preferred_watch_timeout", None)
        if pref is not None:
            self.preferred_watch_timeout = pref

    def _maybe_fail(self) -> None:
        kind = self.plan.fire("kube.request")
        if kind is None:
            return
        if kind == "delay":
            time.sleep(self.plan.sites["kube.request"].delay_s)
            return
        if kind == "conn-reset":
            raise ConnectionResetError("injected: connection reset")
        code = {"http-429": 429, "http-500": 500}.get(kind, 503)
        err = InjectedApiError(f"injected: HTTP {code}")
        err.code = code
        raise err

    def create(self, kind: str, obj: dict) -> dict:
        self._maybe_fail()
        return self.inner.create(kind, obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        self._maybe_fail()
        return self.inner.get(kind, namespace, name)

    def list(self, kind, namespace=None, label_selector=None):
        self._maybe_fail()
        return self.inner.list(kind, namespace=namespace,
                               label_selector=label_selector)

    def update(self, kind: str, obj: dict) -> dict:
        self._maybe_fail()
        return self.inner.update(kind, obj)

    def patch(self, kind, namespace, name, patch):
        self._maybe_fail()
        return self.inner.patch(kind, namespace, name, patch)

    def patch_status(self, kind, namespace, name, patch):
        self._maybe_fail()
        return self.inner.patch_status(kind, namespace, name, patch)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._maybe_fail()
        self.inner.delete(kind, namespace, name)

    def watch(self, kind, namespace=None, replay=True, timeout=None,
              resource_version=None) -> Iterator[WatchEvent]:
        stream = self.inner.watch(
            kind, namespace=namespace, replay=replay, timeout=timeout,
            resource_version=resource_version,
        )

        def _faulty() -> Iterator[WatchEvent]:
            for ev in stream:
                fault = self.plan.fire("kube.watch")
                if fault == "disconnect":
                    return  # stream cut mid-flight; consumer resumes
                if fault == "delay":
                    # the injected stall is the fault being modeled
                    time.sleep(  # slicelint: disable=sleep-in-loop
                        self.plan.sites["kube.watch"].delay_s)
                yield ev

        return _faulty()


# ------------------------------------------------------------- device

class FaultyBackend:
    """Injects device flakiness in front of a
    :class:`~instaslice_tpu.device.backend.DeviceBackend`. Sites
    ``device.<op>`` for op in reserve/release/list/discover/health;
    kinds: ``error`` (DeviceError), ``delay`` (slow ioctl), and
    ``chip-fail`` (marks a random chip unhealthy through the inner
    backend's ``fail_chip`` — the health sweep then sees it, exactly
    like an ICI link drop)."""

    def __init__(self, inner, plan: FaultPlan) -> None:
        self._inner = inner
        self._plan = plan

    def __getattr__(self, name):  # passthrough (test helpers included)
        return getattr(self._inner, name)

    def _maybe_fail(self, op: str) -> None:
        from instaslice_tpu.device.backend import DeviceError

        kind = self._plan.fire(f"device.{op}")
        if kind is None:
            return
        if kind == "delay":
            time.sleep(self._plan.sites[f"device.{op}"].delay_s)
            return
        if kind == "chip-fail":
            fail = getattr(self._inner, "fail_chip", None)
            if fail is not None:
                inv = self._inner.discover()
                chips = sorted(inv.chip_paths)
                fail(chips[self._plan.randrange(len(chips))])
            return
        raise DeviceError(f"injected device.{op} failure")

    def discover(self):
        self._maybe_fail("discover")
        return self._inner.discover()

    def reserve(self, slice_uuid, chip_ids):
        self._maybe_fail("reserve")
        return self._inner.reserve(slice_uuid, chip_ids)

    def release(self, slice_uuid):
        self._maybe_fail("release")
        return self._inner.release(slice_uuid)

    def list_reservations(self):
        self._maybe_fail("list")
        return self._inner.list_reservations()

    def chip_health(self):
        self._maybe_fail("health")
        return self._inner.chip_health()


# ------------------------------------------------------------- engine

def poison_cache(engine) -> None:
    """Consume the engine's donated KV-cache buffers — byte-for-byte
    the state a failed jitted call leaves behind (``cache_poisoned()``
    turns True; only ``recover()`` makes the engine decode again)."""
    import jax

    trees = [engine.cache]
    if getattr(engine, "draft_model", None) is not None:
        trees.append(engine.draft_cache)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            delete = getattr(leaf, "delete", None)
            if delete is not None and not leaf.is_deleted():
                delete()


def engine_fault_hook(plan: FaultPlan, engine) -> Callable[[str], None]:
    """The callable for ``engine.fault_hook``: consulted with the op
    name (``"prefill"``/``"decode"``/``"spec"``) before each dispatch.
    Sites ``engine.<op>``; kinds: ``delay`` (slow dispatch), ``poison``
    (chip failure mid-dispatch: the donated cache is consumed AND the
    call raises — the full recovery path), ``error`` (host-side raise,
    cache intact)."""

    def hook(op: str) -> None:
        site = f"engine.{op}"
        kind = plan.fire(site)
        if kind is None:
            return
        if kind == "delay":
            time.sleep(plan.sites[site].delay_s)
            return
        if kind == "poison":
            poison_cache(engine)
            raise FaultError(f"injected chip failure during {op} "
                             "(cache consumed)")
        raise FaultError(f"injected {op} failure")

    return hook


def scheduler_fault_hook(plan: FaultPlan) -> Callable[[], None]:
    """Hook for the API scheduler's loop (site ``scheduler.round``):
    ``delay`` stalls a round, ``error`` raises into the loop's guard —
    proving one bad round never kills the serving thread."""

    def hook() -> None:
        kind = plan.fire("scheduler.round")
        if kind is None:
            return
        if kind == "delay":
            time.sleep(plan.sites["scheduler.round"].delay_s)
            return
        raise FaultError("injected scheduler-round failure")

    return hook
