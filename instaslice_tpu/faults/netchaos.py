"""Network nemesis: seeded, schedulable network-fault injection.

:mod:`instaslice_tpu.faults` models *endpoint* misbehavior — a flaky
API server, a failing device ioctl, a poisoned engine dispatch. This
module models the **network between** endpoints: partitions (symmetric
and one-way), added latency/jitter, probabilistic drops, duplicated and
reordered watch deliveries, and slow-transfer throttling, all driven by
one seeded :class:`NemesisPlan` so a red chaos run replays exactly.

The plan speaks in directed **links** ``src>dst`` between named
endpoints (``controller``, ``agent-node-0``, ``router``,
``replica:http://…``, ``apiserver``, ``loadgen``, ``opstream``).
Injection happens at the transport seams:

- :class:`NemesisKubeClient` wraps any kube client (controller↔apiserver
  and agent↔apiserver): verbs consult the ``ident>apiserver`` edge,
  watch deliveries the reverse ``apiserver>ident`` edge — which is what
  makes **one-way** partitions real (a controller that can still write
  but sees no watch events, or the mirror image).
- The router consults its plan on the ``router>replica:<url>`` edge
  around every replica HTTP call and stream chunk
  (``serving/router.py``).
- The distributed op-stream consults ``opstream>follower:<addr>`` per
  broadcast (``serving/distributed.py``).

Every rule can carry ``start``/``duration`` offsets, so scenarios are
*scheduled*: partition at t=1s, heal at t=3s — the *timed heal* is what
lets every nemesis test end in a convergence check. :meth:`NemesisPlan.
heal` force-heals immediately.

Plans are built in tests or parsed from ``TPUSLICE_NEMESIS_PLAN``::

    TPUSLICE_NEMESIS_PLAN="seed=7;controller>apiserver:kind=partition,start=1,duration=2;router>replica:*:kind=latency,delay=0.05,jitter=0.02"

Grammar: ``seed=N`` then ``;``-separated ``src>dst:key=val,...`` rules
(the *last* ``:`` splits link from body, so ``replica:*`` works as a
dst). Keys: ``kind`` (``partition``/``partition-oneway``/``latency``/
``drop``/``dup``/``reorder``/``disconnect``/``expire``/``throttle``),
``p`` (probability for the stochastic kinds), ``delay``/``jitter``
(seconds), ``rate`` (bytes/s for ``throttle``), ``start``/``duration``
(seconds from :meth:`NemesisPlan.start`; no duration = until heal),
``max`` (fire cap). ``src``/``dst`` are fnmatch patterns.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from fnmatch import fnmatchcase
from typing import Dict, Iterator, List, Optional

from instaslice_tpu.kube.client import (
    KubeClient,
    ResourceVersionExpired,
    WatchEvent,
)
from instaslice_tpu.utils.lockcheck import named_lock

#: rule kinds a plan accepts (``partition`` is symmetric; everything
#: else applies to the rule's directed edge only)
NEMESIS_KINDS = (
    "partition", "partition-oneway", "latency", "drop", "dup",
    "reorder", "disconnect", "expire", "throttle",
)

#: watch-delivery kinds :meth:`NemesisPlan.watch_action` can return
_WATCH_KINDS = ("drop", "dup", "reorder", "disconnect", "expire")


class PartitionError(ConnectionError):
    """The network between two endpoints is partitioned (injected).

    Derives :class:`ConnectionError` so every transport-error handler —
    the kube retry layer, the router's breaker audit, the agent's
    degraded-mode detection — sees exactly what a real partition
    surfaces: a connection-level failure, not an API answer."""

    def __init__(self, src: str, dst: str) -> None:
        super().__init__(f"injected partition: {src} -/-> {dst}")
        self.src = src
        self.dst = dst


@dataclass
class NemesisRule:
    """One scheduled misbehavior on a directed link (see module
    docstring for the field semantics)."""

    src: str
    dst: str
    kind: str
    probability: float = 1.0
    delay_s: float = 0.05
    jitter_s: float = 0.0
    rate_bps: float = 0.0
    start_s: float = 0.0
    duration_s: float = -1.0     # -1 = until heal()
    max_fires: int = -1          # -1 = unlimited
    healed: bool = False
    fired: int = 0

    def matches(self, src: str, dst: str) -> bool:
        if fnmatchcase(src, self.src) and fnmatchcase(dst, self.dst):
            return True
        # a symmetric partition severs both directions of its link
        return self.kind == "partition" and (
            fnmatchcase(src, self.dst) and fnmatchcase(dst, self.src)
        )


class NemesisPlan:
    """Seeded, schedulable network-fault plan. Thread-safe: control-
    plane workers, the router's proxy threads, and the poll loop all
    consult it concurrently; every RNG draw happens under the plan
    lock so the same seed replays the same fault sequence."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: List[NemesisRule] = []
        self._lock = named_lock("faults.nemesis")
        self._t0 = time.monotonic()

    # ------------------------------------------------------------ schedule

    def start(self) -> "NemesisPlan":
        """Re-anchor the schedule clock (``start``/``duration`` offsets
        count from here). Building a plan anchors it too — call this
        when the scenario begins later than plan construction."""
        with self._lock:
            self._t0 = time.monotonic()
        return self

    def rule(self, src: str, dst: str, kind: str, **kw) -> NemesisRule:
        if kind not in NEMESIS_KINDS:
            raise ValueError(
                f"unknown nemesis kind {kind!r} (want one of "
                f"{'/'.join(NEMESIS_KINDS)})"
            )
        r = NemesisRule(src=src, dst=dst, kind=kind, **kw)
        with self._lock:
            self.rules.append(r)
        return r

    # convenience constructors (what tests read best)

    def partition(self, src: str, dst: str, start: float = 0.0,
                  duration: float = -1.0) -> NemesisRule:
        """Symmetric partition: both directions of the link are cut."""
        return self.rule(src, dst, "partition", start_s=start,
                         duration_s=duration)

    def partition_oneway(self, src: str, dst: str, start: float = 0.0,
                         duration: float = -1.0) -> NemesisRule:
        """Cut ONLY ``src``→``dst``; the reverse direction still flows."""
        return self.rule(src, dst, "partition-oneway", start_s=start,
                         duration_s=duration)

    def latency(self, src: str, dst: str, delay: float,
                jitter: float = 0.0, start: float = 0.0,
                duration: float = -1.0) -> NemesisRule:
        return self.rule(src, dst, "latency", delay_s=delay,
                         jitter_s=jitter, start_s=start,
                         duration_s=duration)

    def drop(self, src: str, dst: str, p: float, start: float = 0.0,
             duration: float = -1.0, max_fires: int = -1) -> NemesisRule:
        return self.rule(src, dst, "drop", probability=p,
                         start_s=start, duration_s=duration,
                         max_fires=max_fires)

    def watch_chaos(self, src: str, dst: str, dup_p: float = 0.0,
                    reorder_p: float = 0.0) -> List[NemesisRule]:
        """Duplicated + reordered watch deliveries on ``src``→``dst``
        (``src`` is the server side for watches: ``apiserver``)."""
        out = []
        if dup_p > 0:
            out.append(self.rule(src, dst, "dup", probability=dup_p))
        if reorder_p > 0:
            out.append(self.rule(src, dst, "reorder",
                                 probability=reorder_p))
        return out

    def throttle(self, src: str, dst: str, rate_bps: float,
                 start: float = 0.0,
                 duration: float = -1.0) -> NemesisRule:
        return self.rule(src, dst, "throttle", rate_bps=rate_bps,
                         start_s=start, duration_s=duration)

    def heal(self, src: str = "*", dst: str = "*") -> int:
        """Force-heal every rule whose link matches; returns how many.
        (Timed rules heal themselves when ``duration`` elapses.)"""
        n = 0
        with self._lock:
            for r in self.rules:
                if (not r.healed and fnmatchcase(r.src, src)
                        and fnmatchcase(r.dst, dst)):
                    r.healed = True
                    n += 1
        return n

    # ------------------------------------------------------------ matching

    def _active(self, src: str, dst: str) -> List[NemesisRule]:
        """Rules live on the directed edge ``src``→``dst`` right now.
        Caller holds no lock; we take it (fire-cap bookkeeping happens
        later, under the lock, in the consult methods)."""
        now = time.monotonic()
        with self._lock:
            elapsed = now - self._t0
            out = []
            for r in self.rules:
                if r.healed or not r.matches(src, dst):
                    continue
                if elapsed < r.start_s:
                    continue
                if 0 <= r.duration_s < elapsed - r.start_s:
                    continue
                if 0 <= r.max_fires <= r.fired:
                    continue
                out.append(r)
            return out

    def _fires(self, r: NemesisRule) -> bool:
        """Probability draw + fire-cap bump (under the plan lock)."""
        with self._lock:
            if 0 <= r.max_fires <= r.fired:
                return False
            if r.probability < 1.0 and self.rng.random() >= r.probability:
                return False
            r.fired += 1
            return True

    def _jittered(self, r: NemesisRule) -> float:
        if r.jitter_s <= 0:
            return r.delay_s
        with self._lock:
            return r.delay_s + self.rng.uniform(0, r.jitter_s)

    def is_partitioned(self, src: str, dst: str) -> bool:
        return any(r.kind in ("partition", "partition-oneway")
                   for r in self._active(src, dst))

    # ------------------------------------------------------------ consults

    def before_request(self, src: str, dst: str) -> None:
        """One request attempt ``src``→``dst``: raises
        :class:`PartitionError` under a partition or a fired drop;
        sleeps under a latency rule."""
        for r in self._active(src, dst):
            if r.kind in ("partition", "partition-oneway"):
                with self._lock:
                    r.fired += 1
                raise PartitionError(src, dst)
            if r.kind == "drop" and self._fires(r):
                raise PartitionError(src, dst)
            if r.kind == "latency" and self._fires(r):
                # the injected stall IS the fault being modeled
                time.sleep(self._jittered(r))  # slicelint: disable=sleep-in-loop

    def watch_action(self, src: str, dst: str) -> Optional[str]:
        """One watch delivery ``src``→``dst`` (``src`` = the server).
        Returns ``"drop"``/``"dup"``/``"reorder"``/``"disconnect"``/
        ``"expire"`` or None; applies latency inline. A partition on
        the delivery edge reads as ``"disconnect"`` — the stream is
        cut and re-establishment then fails loudly at the verb edge."""
        for r in self._active(src, dst):
            if r.kind in ("partition", "partition-oneway"):
                with self._lock:
                    r.fired += 1
                return "disconnect"
            if r.kind == "latency" and self._fires(r):
                # the injected stall IS the fault being modeled
                time.sleep(self._jittered(r))  # slicelint: disable=sleep-in-loop
                continue
            if r.kind in _WATCH_KINDS and self._fires(r):
                return r.kind
        return None

    def throttle_sleep(self, src: str, dst: str, nbytes: int) -> None:
        """Slow-transfer model: sleep ``nbytes``/rate for the slowest
        active throttle on the edge."""
        rate = 0.0
        for r in self._active(src, dst):
            if r.kind == "throttle" and r.rate_bps > 0:
                rate = min(rate, r.rate_bps) if rate else r.rate_bps
                with self._lock:
                    r.fired += 1
        if rate > 0 and nbytes > 0:
            time.sleep(nbytes / rate)

    # --------------------------------------------------------------- stats

    def stats(self) -> List[dict]:
        """Per-rule fire counts — chaos tests log this on failure so a
        regression names the fault sequence that broke it."""
        with self._lock:
            return [
                {"link": f"{r.src}>{r.dst}", "kind": r.kind,
                 "fired": r.fired, "healed": r.healed}
                for r in self.rules
            ]

    # ----------------------------------------------------------------- env

    @classmethod
    def from_env(cls, text: Optional[str] = None) -> Optional["NemesisPlan"]:
        """Parse ``TPUSLICE_NEMESIS_PLAN`` (module-docstring grammar).
        Returns None for empty/missing text."""
        if text is None:
            text = os.environ.get("TPUSLICE_NEMESIS_PLAN", "")
        text = (text or "").strip()
        if not text:
            return None
        seed = 0
        rules: List[tuple] = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                seed = int(part[len("seed="):])
                continue
            if ":" not in part or ">" not in part:
                raise ValueError(
                    f"TPUSLICE_NEMESIS_PLAN: malformed rule {part!r} "
                    f"(want src>dst:key=val,...)"
                )
            link, body = part.rsplit(":", 1)
            src, _, dst = link.partition(">")
            kw: dict = {}
            kind = ""
            for item in body.split(","):
                if not item.strip():
                    continue
                key, _, val = item.partition("=")
                key = key.strip()
                if key == "kind":
                    kind = val.strip()
                elif key == "p":
                    kw["probability"] = float(val)
                elif key == "delay":
                    kw["delay_s"] = float(val)
                elif key == "jitter":
                    kw["jitter_s"] = float(val)
                elif key == "rate":
                    kw["rate_bps"] = float(val)
                elif key == "start":
                    kw["start_s"] = float(val)
                elif key == "duration":
                    kw["duration_s"] = float(val)
                elif key == "max":
                    kw["max_fires"] = int(val)
                else:
                    raise ValueError(
                        f"TPUSLICE_NEMESIS_PLAN: unknown key {key!r} "
                        f"in {part!r}"
                    )
            if not kind:
                raise ValueError(
                    f"TPUSLICE_NEMESIS_PLAN: rule {part!r} needs kind="
                )
            rules.append((src.strip(), dst.strip(), kind, kw))
        plan = cls(seed)
        for src, dst, kind, kw in rules:
            plan.rule(src, dst, kind, **kw)
        return plan


#: the process-default nemesis plan — None (the overwhelmingly common
#: case) costs one global read per seam visit
_nemesis: Optional[NemesisPlan] = NemesisPlan.from_env()


def set_nemesis(plan: Optional[NemesisPlan]) -> None:
    """Install the process nemesis plan (tests / chaos drivers)."""
    global _nemesis
    _nemesis = plan


def get_nemesis() -> Optional[NemesisPlan]:
    return _nemesis


def reset_nemesis() -> None:
    """Re-read ``TPUSLICE_NEMESIS_PLAN`` (test isolation)."""
    global _nemesis
    _nemesis = NemesisPlan.from_env()


# ----------------------------------------------------------------- kube

class NemesisKubeClient(KubeClient):
    """Injects network behavior between one identified consumer and
    the API server. ``ident`` names the consumer (``controller``,
    ``agent-node-0``); verbs consult the ``ident>apiserver`` edge,
    watch deliveries the reverse ``apiserver>ident`` edge — one-way
    partitions behave asymmetrically exactly like iptables rules
    would. Composes with :class:`~instaslice_tpu.faults.
    FaultyKubeClient` and both the fake and real clients."""

    SERVER = "apiserver"

    def __init__(self, inner: KubeClient, plan: NemesisPlan,
                 ident: str) -> None:
        self.inner = inner
        self.plan = plan
        self.ident = ident
        pref = getattr(inner, "preferred_watch_timeout", None)
        if pref is not None:
            self.preferred_watch_timeout = pref

    def _pre(self) -> None:
        self.plan.before_request(self.ident, self.SERVER)

    def create(self, kind: str, obj: dict) -> dict:
        self._pre()
        return self.inner.create(kind, obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        self._pre()
        return self.inner.get(kind, namespace, name)

    def list(self, kind, namespace=None, label_selector=None):
        self._pre()
        return self.inner.list(kind, namespace=namespace,
                               label_selector=label_selector)

    def update(self, kind: str, obj: dict) -> dict:
        self._pre()
        return self.inner.update(kind, obj)

    def patch(self, kind, namespace, name, patch):
        self._pre()
        return self.inner.patch(kind, namespace, name, patch)

    def patch_status(self, kind, namespace, name, patch):
        self._pre()
        return self.inner.patch_status(kind, namespace, name, patch)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._pre()
        self.inner.delete(kind, namespace, name)

    def watch(self, kind, namespace=None, replay=True, timeout=None,
              resource_version=None) -> Iterator[WatchEvent]:
        self._pre()  # establishment rides the request edge
        stream = self.inner.watch(
            kind, namespace=namespace, replay=replay, timeout=timeout,
            resource_version=resource_version,
        )
        plan, server, ident = self.plan, self.SERVER, self.ident

        def _nemesis_stream() -> Iterator[WatchEvent]:
            held: Optional[WatchEvent] = None
            for ev in stream:
                act = plan.watch_action(server, ident)
                if act == "disconnect":
                    return          # stream cut mid-flight
                if act == "expire":
                    raise ResourceVersionExpired(
                        "injected: watch resourceVersion expired (410)"
                    )
                if act == "drop":
                    continue
                if act == "dup":
                    yield ev
                    yield ev
                    continue
                if act == "reorder" and held is None:
                    held = ev       # deliver AFTER the next event
                    continue
                yield ev
                if held is not None:
                    yield held
                    held = None
            if held is not None:
                yield held

        return _nemesis_stream()
