"""Deprecated alias package — the slice-consumer SDK moved to
:mod:`instaslice_tpu.parallel` (meshenv, ring), :mod:`instaslice_tpu.models`
(lm, train), :mod:`instaslice_tpu.ops` (pallas kernels) and
:mod:`instaslice_tpu.serving` (engine). Old import paths keep working via
the module aliases below.
"""

import sys

from instaslice_tpu.models import lm as model
from instaslice_tpu.models import train
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.train import TrainState, make_train_step
from instaslice_tpu.parallel import meshenv, ring
from instaslice_tpu.parallel.meshenv import (
    SliceTopology,
    initialize_distributed,
    slice_mesh,
)

sys.modules[__name__ + ".model"] = model
sys.modules[__name__ + ".train"] = train
sys.modules[__name__ + ".meshenv"] = meshenv
sys.modules[__name__ + ".ring"] = ring

__all__ = [
    "SliceTopology",
    "initialize_distributed",
    "slice_mesh",
    "ModelConfig",
    "TpuLM",
    "TrainState",
    "make_train_step",
]
