"""Slice-consumer SDK: what runs *inside* a granted pod.

The reference ships workloads only as sample YAML (cuda vectoradd, TF
notebook, vLLM — ``/root/reference/samples/``, SURVEY.md §1 "Workloads ...
are *consumers* ... not part of the framework"). For a TPU slice that is
not enough: a slice is defined by its ICI mesh, so the consumer needs real
library support to (a) reconstruct the mesh from the handoff env the node
agent publishes (``agent/handoff.py``) and (b) shard its computation over
it with jax/pjit. This package provides both, plus a flagship sharded
transformer LM used by the samples, the benchmarks, and
``__graft_entry__.py``.
"""

from instaslice_tpu.workload.meshenv import (
    SliceTopology,
    initialize_distributed,
    slice_mesh,
)
from instaslice_tpu.workload.model import ModelConfig, TpuLM
from instaslice_tpu.workload.train import TrainState, make_train_step

__all__ = [
    "SliceTopology",
    "initialize_distributed",
    "slice_mesh",
    "ModelConfig",
    "TpuLM",
    "TrainState",
    "make_train_step",
]
