"""Flash attention as a pallas TPU kernel.

Why a kernel at all: XLA materializes the (S, S) attention logits in HBM
for the naive formulation; the online-softmax formulation streams K/V
blocks through VMEM and keeps per-row running (max, sum, acc) statistics,
so HBM traffic drops from O(S²) to O(S·d) — the standard flash-attention
trade mapped onto the TPU memory hierarchy (HBM → VMEM → MXU).

Kernel shape choices, per the pallas guide:
- grid = (batch·heads, S / block_q): one program per query block; the MXU
  sees (block_q, hd) × (hd, block_k) matmuls with fp32 accumulation
  (``preferred_element_type``).
- K/V ride in VMEM whole per (batch, head) program — at bf16 and
  S ≤ 4k, hd ≤ 256 that is ≤ 2 MB each, inside the ~16 MB VMEM budget;
  the causal mask is built with ``broadcasted_iota`` (2-D, TPU rule).
- fp32 accumulators; output cast back to the input dtype.
- backward is blockwise too: the forward saves only (o, lse); two kernels
  recompute softmax probabilities per block from (q, k, lse) and
  accumulate dq (one query block vs streamed K/V) and dk/dv (one K/V
  block vs streamed queries, starting at the causal diagonal) — training
  never materializes the (S, S) logits either.

Off-TPU the same kernel runs in interpreter mode so tests exercise the
real kernel logic on CPU; ``flash_attention`` also falls back to the XLA
formulation for shapes the kernel does not tile (S not a multiple of the
block size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# numpy, not jnp: a module-level jnp scalar would initialize the jax
# backend at import time, locking the platform before consumers can
# configure it
_NEG = np.float32(-1e30)

#: Trailing lane width for the per-row (lse, delta) tensors. TPU pallas
#: requires each block's last two dims to be (8, 128)-divisible or equal
#: to the array dims, so a bare (1, block_q) row-vector block does not
#: lower; the row statistics are broadcast across a small trailing lane
#: dim instead (the same trick as jax's own TPU flash kernel, which uses
#: 128 lanes — 8 satisfies the "equal to the array dim" clause at 1/16th
#: the HBM).
_LANES = 8


def _xla_attention(q, k, v, causal: bool) -> jax.Array:
    """Reference formulation (used as fallback and in tests)."""
    hd = q.shape[-1]
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (hd ** -0.5)
    if causal:
        S, K = q.shape[1], k.shape[1]
        # queries are the LAST S positions of the kv sequence (decode-style
        # cropped-query attention): query row i sits at absolute position
        # i + K - S, so key j is visible iff j <= i + K - S
        mask = (
            jax.lax.broadcasted_iota(jnp.int32, (S, K), 0) + (K - S)
            >= jax.lax.broadcasted_iota(jnp.int32, (S, K), 1)
        )
        logits = jnp.where(mask[None, None], logits, _NEG)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *,
    block_k: int, causal: bool, sm_scale: float,
):
    """One query block vs all K/V blocks with online softmax. Also emits
    the per-row logsumexp (lse) so the backward kernels can recompute
    softmax probabilities blockwise instead of saving them."""
    q = q_ref[0].astype(jnp.float32) * sm_scale          # (BQ, hd)
    block_q, hd = q.shape
    kv_len = k_ref.shape[1]
    n_blocks = kv_len // block_k
    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # (BQ, BK)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    if causal:
        # blocks strictly above the diagonal contribute nothing; the loop
        # bound is data-independent (derived from program_id), so this is
        # still a static-shape friendly bound
        n_live = jnp.minimum(
            n_blocks, ((qi + 1) * block_q + block_k - 1) // block_k
        )
    else:
        n_live = n_blocks
    m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_live, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (block_q, _LANES))


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
    block_k: int, causal: bool, sm_scale: float,
):
    """dq for one query block: recompute p blockwise from (q, k, lse),
    ds = p * (dp - delta), dq += ds @ k — never an (S, S) tensor."""
    q = q_ref[0].astype(jnp.float32)                      # (BQ, hd)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]                               # (BQ, 1)
    delta = delta_ref[0][:, :1]
    block_q, hd = q.shape
    kv_len = k_ref.shape[1]
    n_blocks = kv_len // block_k
    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)                              # (BQ, BK)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        return acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        n_live = jnp.minimum(
            n_blocks, ((qi + 1) * block_q + block_k - 1) // block_k
        )
    else:
        n_live = n_blocks
    acc = jax.lax.fori_loop(
        0, n_live, body, jnp.zeros((block_q, hd), jnp.float32)
    )
    dq_ref[0] = (sm_scale * acc).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
    block_q: int, causal: bool, sm_scale: float,
):
    """dk/dv for one K/V block: stream query blocks (from the diagonal
    when causal), recomputing p from (q, k, lse) per block."""
    k = k_ref[0].astype(jnp.float32)                      # (BK, hd)
    v = v_ref[0].astype(jnp.float32)
    block_k, hd = k.shape
    S = q_ref.shape[1]
    n_q_blocks = S // block_q
    kj = pl.program_id(1)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :][:, :1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :][:, :1]
        s = sm_scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # (BQ, BK)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # (BK, hd)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    # causal: query blocks strictly before this K/V block's diagonal see
    # none of it; start the stream at the diagonal (program_id-derived —
    # static-shape friendly)
    start = (kj * block_k) // block_q if causal else 0
    zeros = jnp.zeros((block_k, hd), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, n_q_blocks, body, (zeros, zeros))
    dk_ref[0] = (sm_scale * dk).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_call(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_call(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_call(
        q, k, v, o, lse, g, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_bwd_call(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    BH, S, hd = q.shape
    kv_len = k.shape[1]
    # delta[b, i] = rowsum(do * o) — O(S·hd), fine in plain XLA; broadcast
    # across the lane dim so its blocks tile like lse's (see _LANES)
    delta = jnp.broadcast_to(
        jnp.sum(
            g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )[:, :, None],
        (BH, S, _LANES),
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_k=block_k, causal=causal, sm_scale=hd ** -0.5,
        ),
        out_shape=jax.ShapeDtypeStruct((BH, S, hd), q.dtype),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_len, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_len, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=block_q, causal=causal, sm_scale=hd ** -0.5,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, kv_len, hd), k.dtype),
            jax.ShapeDtypeStruct((BH, kv_len, hd), v.dtype),
        ),
        grid=(BH, kv_len // block_k),
        in_specs=[
            pl.BlockSpec((1, S, hd), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, S, hd), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, S, _LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, S, _LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, hd), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, hd), lambda b, j: (b, j, 0)),
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_call(q, k, v, causal, block_q, block_k, interpret):
    BH, S, hd = q.shape
    kv_len = k.shape[1]
    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=hd ** -0.5,
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((BH, S, hd), q.dtype),
            # logsumexp, lane-broadcast (see _LANES)
            jax.ShapeDtypeStruct((BH, S, _LANES), jnp.float32),
        ),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_len, hd), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, kv_len, hd), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, hd), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
        ),
        interpret=interpret,
    )(q, k, v)


def _fit_block(pref: int, size: int) -> int:
    """Largest block ≤ ``pref`` that divides ``size`` (halving from
    ``pref``); 0 when none works (caller falls back to XLA). A partial
    block must be a multiple of the 8-row sublane tile; a block equal to
    the whole axis is always legal (the "equal to the array dim" clause
    of the TPU tiling rule)."""
    b = min(pref, size)
    while b >= 8 and size % b:
        b //= 2
    if b < 8 or size % b:
        return 0
    if b != size and b % 8:
        return 0
    return b


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Attention over (B, S, H, hd) q/k/v, flash-style.

    Matches :func:`_xla_attention` up to fp accumulation order. Shapes the
    kernel cannot tile (sequence not a multiple of the block size) fall
    back to the XLA formulation rather than failing.

    Default blocks (256, 512) are from an on-chip sweep at
    B=4 S=2048 H=8 hd=128 on v5e: fwd 78.7 / bwd 92.9 TFLOP/s vs 18.9 /
    26.9 at (128, 128) — the MXU wants the bigger tiles, and the VPU's
    per-block (max, exp, rescale) work amortizes over 4× more matmul
    FLOPs. (BENCH_LOCAL_r03.json records the resulting vs-XLA speedups.)
    """
    B, S, H, hd = q.shape
    kv_len = k.shape[1]
    # halve the preferred blocks until they tile the sequence (e.g.
    # S=384 → bq 128): losing some block size still beats falling all
    # the way back to the O(S²)-HBM XLA path. Floor 8 = the TPU sublane
    # tile the kernel's block specs must respect.
    bq = _fit_block(block_q, S)
    bk = _fit_block(block_k, kv_len)
    if not bq or not bk or (causal and S != kv_len):
        return _xla_attention(q, k, v, causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, S, hd)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, kv_len, hd)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, kv_len, hd)
    out = _flash(qt, kt, vt, causal, bq, bk, interpret)
    return out.reshape(B, H, S, hd).transpose(0, 2, 1, 3)
