"""int8-weight × float-activation matmul as a pallas TPU kernel (w8a16).

Why a kernel: weight-only int8 halves the bytes a decode step streams
only if the int8 bytes are what actually cross HBM. XLA cannot fuse an
elementwise producer into a ``dot`` operand — the dequantized bf16
weight is materialized in HBM first, so the quantized path costs
int8-read + bf16-write + bf16-read ≈ 5 bytes/param/step instead of 1.
The 2026-07-31 on-chip capture showed exactly that: the 7B int8 decode
step took ~36 ms at batch 32 ≈ the 34 GB the materialized path streams
at v5e's ~819 GB/s, not the ~8.4 ms the int8 bytes alone would take.

This kernel streams int8 weight tiles HBM→VMEM, converts to the
activation dtype inside VMEM (exact: int8 values are integers ≤ 127),
feeds the MXU with fp32 accumulation, and applies the per-output-channel
fp32 scale once to the accumulated output block — mathematically
identical to dequantize-then-dot because the scale is constant along the
contraction:  Σ_k x_k (q_kn s_n) = s_n Σ_k x_k q_kn.  Only the int8
bytes ever cross HBM. (Slightly *more* accurate than the XLA fallback,
which rounds q·s to bf16 before the dot; here the scale stays fp32.)

Decode is the target: M = batch (8–64) rows against (K, N) weights of
4k–20k, purely bandwidth-bound, so the win is the 5×→1× byte ratio.
Prefill (M in the thousands) is compute-bound and stays on the XLA path
— the materialized dequant amortizes over thousands of rows there.

Reference analog: the reference operator has no compute kernels at all
(SURVEY.md §1 — no ops layer); this belongs to the TPU-first serving
stack built around the granted slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def quant_matmul_ref(x: jax.Array, q: jax.Array, s: jax.Array,
                     transpose_w: bool = False) -> jax.Array:
    """Reference formulation: dequantize (fp32 scale) then dot. Used as
    the numerical oracle in tests and the fallback for shapes the kernel
    does not tile."""
    w = q.astype(jnp.float32) * s.astype(jnp.float32)
    w = w.astype(x.dtype)
    sub = "mk,nk->mn" if transpose_w else "mk,kn->mn"
    return jnp.einsum(sub, x, w, preferred_element_type=jnp.float32)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, *, transpose_w: bool):
    """One (M, block_n) output block accumulated over the k grid axis.

    The output block is revisited across k steps (its index map ignores
    the k program id); step 0 zeroes it, the last step applies the
    per-column scale to the finished fp32 accumulator.
    """
    kj = pl.program_id(1)

    @pl.when(kj == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...]                               # (M, bk) activations
    w = w_ref[...].astype(x.dtype)               # int8 → exact in bf16
    contract = ((1,), (1,)) if transpose_w else ((1,), (0,))
    o_ref[...] += jax.lax.dot_general(
        x, w, (contract, ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kj == pl.num_programs(1) - 1)
    def _scale():
        o_ref[...] = o_ref[...] * s_ref[...]     # (1, bn) fp32


@functools.partial(
    jax.jit,
    static_argnames=("transpose_w", "block_k", "block_n", "interpret"),
)
def _qmm_call(x, q, s, transpose_w, block_k, block_n, interpret):
    M, K = x.shape
    N = q.shape[0] if transpose_w else q.shape[1]
    if transpose_w:
        w_spec = pl.BlockSpec((block_n, block_k), lambda n, k: (n, k))
    else:
        w_spec = pl.BlockSpec((block_k, block_n), lambda n, k: (k, n))
    return pl.pallas_call(
        functools.partial(_qmm_kernel, transpose_w=transpose_w),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        # n outer (parallel output tiles), k inner (accumulation)
        grid=(N // block_n, K // block_k),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda n, k: (0, k)),
            w_spec,
            pl.BlockSpec((1, block_n), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((M, block_n), lambda n, k: (0, n)),
        interpret=interpret,
    )(x, q, s)


def _fit_block(pref: int, size: int) -> int:
    """Largest block ≤ ``pref`` dividing ``size`` (halving), floor 128 =
    the TPU lane tile; 0 when none fits (caller falls back to XLA)."""
    b = min(pref, size)
    while b >= 128 and size % b:
        b //= 2
    return b if b >= 128 and size % b == 0 else 0


def quant_matmul(
    x: jax.Array,
    q: jax.Array,
    s: jax.Array,
    *,
    transpose_w: bool = False,
    block_k: int = 1024,
    block_n: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ dequant(q, s)`` with int8 bytes as the only weight HBM
    traffic. Returns fp32 (M, N), matching the model's
    ``preferred_element_type`` convention.

    ``x``: (M, K) activations (bf16/f32). ``q``: int8 weight, (K, N) —
    or (N, K) with ``transpose_w=True`` (the embedding-table layout).
    ``s``: per-output-channel scale, any shape with N total elements.
    Shapes whose K/N no 128-multiple block divides fall back to the XLA
    reference path rather than failing.
    """
    M, K = x.shape
    if transpose_w:
        N, Kw = q.shape
    else:
        Kw, N = q.shape
    if Kw != K:
        raise ValueError(f"contraction mismatch: x K={K}, w K={Kw}")
    bk = _fit_block(block_k, K)
    bn = _fit_block(block_n, N)
    if not bk or not bn:
        return quant_matmul_ref(x, q, s, transpose_w)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s2 = s.astype(jnp.float32).reshape(1, N)
    return _qmm_call(x, q, s2, transpose_w, bk, bn, interpret)
