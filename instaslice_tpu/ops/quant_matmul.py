"""int8-weight × float-activation matmul as a pallas TPU kernel (w8a16).

Why a kernel: weight-only int8 halves the bytes a decode step streams
only if the int8 bytes are what actually cross HBM. XLA materializes
dequantized dot operands — and, in a block-decode scan, hoists the
dequantize out of the step loop entirely, so the XLA path streams the
FULL bf16 weight bytes every step (measured 2026-07-31 on v5e: the 7B
int8 decode step ran at the bf16 roofline, ~16.7 ms/step at batch 8 —
the int8 storage saved HBM *capacity* but zero per-step *bandwidth*).
This kernel streams the int8 bytes and nothing else: weight tiles DMA
HBM→VMEM as int8, convert in-register (exact: int8 values are integers
≤ 127), hit the MXU with fp32 accumulation, and the per-output-channel
fp32 scale lands once on the accumulated output — mathematically
identical to dequantize-then-dot because the scale is constant along
the contraction: Σ_k x_k (q_kn s_n) = s_n Σ_k x_k q_kn.

Tiling (v2 — the v1 lesson): tiles must be FULL ROW WIDTH. A
(block_k, block_n) tile of a row-major (K, N) int8 array DMAs as
block_k short strided segments and gated the v1 kernel to ~240 GB/s
effective (slower than the XLA bf16 path). v2 tiles are

- ``(block_k, N)`` for the (K, N) projection layout: whole rows,
  contiguous DMA; a 1-D grid over k-stripes accumulates into a
  VMEM-resident (M, N) fp32 block (constant out index map);
- ``(block_n, K)`` for the (N, K) embedding layout: whole rows again;
  each grid step computes a finished (M, block_n) output slab, no
  accumulation (x rides whole in VMEM).

Decode is the target: M = batch (8–64) rows against (4k, 4k–20k)
weights, purely bandwidth-bound. Prefill (M in the thousands) is
compute-bound and stays on the XLA path, which also keeps it
shardable under tensor parallelism.

Reference analog: the reference operator has no compute kernels at all
(SURVEY.md §1 — no ops layer); this belongs to the TPU-first serving
stack built around the granted slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: VMEM spending ceiling for one kernel instance: resident operands
#: (fp32 accumulator / whole-x) + 2× the streamed tile (double
#: buffering) must fit under it, leaving ~4 MB of the ~16 MB VMEM for
#: the compiler's own scratch
_VMEM_BUDGET = 12 * 1024 * 1024


def quant_matmul_ref(x: jax.Array, q: jax.Array, s: jax.Array,
                     transpose_w: bool = False) -> jax.Array:
    """Reference formulation: dequantize (fp32 scale) then dot. Used as
    the numerical oracle in tests and the fallback for shapes the kernel
    does not tile."""
    w = q.astype(jnp.float32) * s.astype(jnp.float32)
    w = w.astype(x.dtype)
    sub = "mk,nk->mn" if transpose_w else "mk,kn->mn"
    return jnp.einsum(sub, x, w, preferred_element_type=jnp.float32)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref):
    """k-stripe accumulation for the (K, N) layout: one (block_k, N)
    whole-row weight tile per grid step, output (M, N) resident in VMEM
    across the 1-D grid (constant out index map); step 0 zeroes it, the
    last step applies the per-column scale."""
    kj = pl.program_id(0)

    @pl.when(kj == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...]                               # (M, bk)
    w = w_ref[...].astype(x.dtype)               # int8 → exact
    o_ref[...] += jax.lax.dot_general(
        x, w, ((((1,), (0,))), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kj == pl.num_programs(0) - 1)
    def _scale():
        o_ref[...] = o_ref[...] * s_ref[...]     # (1, N) fp32


def _qmm_t_kernel(x_ref, w_ref, s_ref, o_ref):
    """n-slab kernel for the (N, K) layout: x rides whole in VMEM, each
    grid step streams a (block_n, K) whole-row weight tile and emits a
    finished (M, block_n) output slab — no accumulation, no revisit."""
    x = x_ref[...]                               # (M, K)
    w = w_ref[...].astype(x.dtype)               # (bn, K) int8 → exact
    acc = jax.lax.dot_general(
        x, w, ((((1,), (1,))), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = acc * s_ref[...]                # (1, bn) fp32


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def _qmm_call(x, q, s, block_k, interpret):
    M, K = x.shape
    N = q.shape[1]
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        grid=(K // block_k,),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda k: (0, k)),
            pl.BlockSpec((block_k, N), lambda k: (k, 0)),
            pl.BlockSpec((1, N), lambda k: (0, 0)),
        ],
        out_specs=pl.BlockSpec((M, N), lambda k: (0, 0)),
        interpret=interpret,
    )(x, q, s)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _qmm_t_call(x, q, s, block_n, interpret):
    M, K = x.shape
    N = q.shape[0]
    return pl.pallas_call(
        _qmm_t_kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((M, K), lambda n: (0, 0)),
            pl.BlockSpec((block_n, K), lambda n: (n, 0)),
            pl.BlockSpec((1, block_n), lambda n: (0, n)),
        ],
        out_specs=pl.BlockSpec((M, block_n), lambda n: (0, n)),
        interpret=interpret,
    )(x, q, s)


def _qmm_stacked_kernel(li_ref, x_ref, w_ref, s_ref, o_ref):
    """Layer-indexed k-stripe accumulation: identical math to
    :func:`_qmm_kernel`, but the weight tile DMAs straight out of the
    STACKED (L, K, N) buffer at the prefetched layer index — the index
    map does the layer selection, so the caller never slices the stack.

    Why this exists: a ``lax.scan`` over layers hands each iteration a
    dynamic-slice of the stacked weights. An einsum fuses that slice
    into its operand read; a ``pallas_call`` operand must materialize,
    so the sliced int8 weight is written to a temp buffer and re-read
    EVERY layer — +2 bytes/param/step of pure copy traffic, which
    erased the kernel's whole 2026-07-31 microbench win in-situ
    (measured: +16.6 ms/step on the 6.8 GB 7B stack ≈ exactly
    write+read at HBM speed). Scalar-prefetch indexing reads the tile
    from the original buffer instead.
    """
    del li_ref  # consumed by the index maps, not the body
    kj = pl.program_id(0)

    @pl.when(kj == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...]                               # (M, bk)
    w = w_ref[0].astype(x.dtype)                 # (1, bk, N) int8 tile
    o_ref[...] += jax.lax.dot_general(
        x, w, ((((1,), (0,))), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(kj == pl.num_programs(0) - 1)
    def _scale():
        o_ref[...] = o_ref[...] * s_ref[0]       # (1, 1, N) fp32


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def _qmm_stacked_call(x, q3, s3, layer, block_k, interpret):
    M, K = x.shape
    N = q3.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(K // block_k,),
        in_specs=[
            pl.BlockSpec((M, block_k), lambda k, li: (0, k)),
            pl.BlockSpec((1, block_k, N), lambda k, li: (li[0], k, 0)),
            pl.BlockSpec((1, 1, N), lambda k, li: (li[0], 0, 0)),
        ],
        out_specs=pl.BlockSpec((M, N), lambda k, li: (0, 0)),
    )
    return pl.pallas_call(
        _qmm_stacked_kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), x, q3, s3)


def quant_matmul_stacked(
    x: jax.Array,
    q3: jax.Array,
    s3: jax.Array,
    layer: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ dequant(q3[layer], s3[layer])`` without ever slicing the
    stack: the kernel's index maps select the layer (scalar prefetch),
    so inside a layer loop the int8 bytes of THIS layer are the only
    weight HBM traffic. ``q3``: (L, K, N) int8; ``s3``: (L, 1, N)
    scales; ``layer``: traced int32 index. Falls back to
    slice-dequantize-einsum (which XLA fuses) for untileable shapes.
    """
    M, K = x.shape
    L, Kw, N = q3.shape
    if Kw != K:
        raise ValueError(f"contraction mismatch: x K={K}, w K={Kw}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile_budget = (_VMEM_BUDGET - M * N * 4) // 2
    bk = _stripe_block(K, N + M * x.dtype.itemsize, tile_budget)
    if bk and N % 128 == 0:
        s2 = s3.astype(jnp.float32).reshape(L, 1, N)
        return _qmm_stacked_call(x, q3, s2, layer, bk, interpret)
    w = (q3[layer].astype(jnp.float32)
         * s3[layer].astype(jnp.float32).reshape(1, N)).astype(x.dtype)
    return jnp.einsum("mk,kn->mn", x, w,
                      preferred_element_type=jnp.float32)


def _stripe_block(dim: int, row_bytes: int,
                  budget: int = 4 * 1024 * 1024) -> int:
    """Largest 128-multiple divisor of ``dim`` whose (block × row_bytes)
    tile fits ``budget``; 0 when none does (or the budget is already
    spent). Full downward scan in 128 steps (trace-time only, ≤ dim/128
    iterations): halving alone would miss e.g. 640 | 32000 for the
    vocab axis."""
    if budget <= 0:
        return 0
    cap = min(dim, budget // max(row_bytes, 1))
    b = cap - cap % 128
    while b >= 128:
        if dim % b == 0:
            return b
        b -= 128
    return 0


def quant_matmul(
    x: jax.Array,
    q: jax.Array,
    s: jax.Array,
    *,
    transpose_w: bool = False,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ dequant(q, s)`` with int8 bytes as the only weight HBM
    traffic. Returns fp32 (M, N), matching the model's
    ``preferred_element_type`` convention.

    ``x``: (M, K) activations (bf16/f32). ``q``: int8 weight, (K, N) —
    or (N, K) with ``transpose_w=True`` (the embedding-table layout).
    ``s``: per-output-channel scale, any shape with N total elements.
    Shapes the whole-row tiling cannot cover (a dim with no 128-multiple
    divisor, or resident operands that would blow VMEM) fall back to the
    XLA reference path rather than failing.
    """
    M, K = x.shape
    if transpose_w:
        N, Kw = q.shape
    else:
        Kw, N = q.shape
    if Kw != K:
        raise ValueError(f"contraction mismatch: x K={K}, w K={Kw}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    s2 = s.astype(jnp.float32).reshape(1, N)
    xsz = x.dtype.itemsize
    if transpose_w:
        # x rides whole in VMEM (resident); each grid step streams a
        # (bn, K) weight tile and writes an (M, bn) fp32 slab — both
        # double-buffered, so a bn costs bn·(K + M·4) against what the
        # resident x leaves of the budget
        tile_budget = (_VMEM_BUDGET - M * K * xsz) // 2
        bn = _stripe_block(N, K + M * 4, tile_budget)
        if bn and K % 128 == 0:
            return _qmm_t_call(x, q, s2, bn, interpret)
    else:
        # fp32 (M, N) accumulator rides resident across the k grid;
        # each step streams a (bk, N) weight tile + an (M, bk) x tile,
        # double-buffered: a bk costs bk·(N + M·xsz)
        tile_budget = (_VMEM_BUDGET - M * N * 4) // 2
        bk = _stripe_block(K, N + M * xsz, tile_budget)
        if bk and N % 128 == 0:
            return _qmm_call(x, q, s2, bk, interpret)
    return quant_matmul_ref(x, q, s, transpose_w)
