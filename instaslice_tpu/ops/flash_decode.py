"""Fused decode attention over the int8 KV cache (pallas, opt-in).

The last measured decode binder (docs/PERF.md): reading the int8 cache
through XLA dequantizes into a materialized compute-dtype copy feeding
the attention dots — the depth term ran 2.7–3.7× the raw int8 bytes.
This kernel streams the int8 bytes, dequantizes tile-by-tile in VMEM,
and runs the online-softmax attention for ONE query step (T = 1)
against each row's written prefix. Unlike the w8a16 weight kernel
(which lost end-to-end because XLA hides non-matmul work under its
weight stream), attention is serial with nothing to hide it under —
the overlap objection does not apply.

Design (everything learned on 2026-07-31 baked in):

- operands are the WHOLE stacked head-major cache (L, B, Hkv, S, hd)
  with the layer picked by scalar-prefetch index maps — a scan-sliced
  pallas operand materializes (the +16.6 ms/step lesson);
- grid is (B,) only: per program the full (Hkv, S_attn, hd) int8 K and
  V blocks ride VMEM (≤ 2 MB at S 2048) and an inner ``fori_loop``
  dequantizes 256-position tiles into registers — small grids keep the
  per-program overhead (~1-2 µs each) off the step time;
- per-row validity (`s < lengths[b]`) comes from a scalar-prefetched
  lengths vector; the output is the UNNORMALIZED accumulator plus
  per-(head, group) running (m, l) so the caller merges the current
  step's local entry with the standard online-softmax identity —
  bit-for-bit the joint softmax the XLA path computes.

Opt-in via ``TPUSLICE_DECODE_KERNEL=1`` (trace-time), decode path
only (T = 1, quantized cache, full-causal); everything else keeps the
measured XLA formulation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: trailing lane width for the (m, l) row statistics (same trick as
#: the flash kernel: a bare (G,) vector block does not lower)
_LANES = 8

#: inner dequant tile along the position axis; the engine buckets
#: attends to 256-position steps, so this always divides S_attn
_BLK = 256


def decode_kernel_enabled() -> bool:
    """Opt-in (default off) until the in-situ measurement says
    otherwise — the w8a16 kernel taught us per-op wins can lose
    end-to-end; see docs/PERF.md."""
    return os.environ.get("TPUSLICE_DECODE_KERNEL", "0") == "1"


def _fd_kernel(li_ref, len_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
               o_ref, m_ref, l_ref, *, sm_scale: float, blk: int):
    b = pl.program_id(0)
    q = q_ref[0].astype(jnp.float32) * sm_scale      # (Hkv, G, hd)
    Hkv, G, hd = q.shape
    S = k_ref.shape[3]
    len_b = len_ref[b]
    n_blk = S // blk

    def body(j, carry):
        m, l, acc = carry
        k8 = k_ref[0, 0, :, pl.ds(j * blk, blk), :]
        ks = ks_ref[0, 0, :, pl.ds(j * blk, blk)]
        k = k8.astype(jnp.float32) * ks[..., None]   # (Hkv, blk, hd)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                            # (Hkv, G, blk)
        pos = j * blk + jax.lax.broadcasted_iota(
            jnp.int32, (Hkv, G, blk), 2
        )
        s = jnp.where(pos < len_b, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v8 = v_ref[0, 0, :, pl.ds(j * blk, blk), :]
        vs = vs_ref[0, 0, :, pl.ds(j * blk, blk)]
        v = v8.astype(jnp.float32) * vs[..., None]
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                            # (Hkv, G, hd)
        return m_new, l, acc

    # rows at depth 0 (empty prefix) still run one tile: everything
    # masks to -1e30, l stays ~0, and the caller's merge with the
    # local entry recovers exactly the local-only softmax
    m0 = jnp.full((Hkv, G, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((Hkv, G, 1), jnp.float32)
    a0 = jnp.zeros((Hkv, G, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blk, body, (m0, l0, a0))
    o_ref[0] = acc
    m_ref[0] = jnp.broadcast_to(m, (Hkv, G, _LANES))
    l_ref[0] = jnp.broadcast_to(l, (Hkv, G, _LANES))


@functools.partial(jax.jit, static_argnames=("s_attn", "interpret"))
def _fd_call(q4, k3, ks3, v3, vs3, lengths, li, s_attn, interpret):
    B, Hkv, G, hd = q4.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # layer index, lengths
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, hd), lambda b, li, ln: (b, 0, 0, 0)),
            pl.BlockSpec((1, 1, Hkv, s_attn, hd),
                         lambda b, li, ln: (li[0], b, 0, 0, 0)),
            pl.BlockSpec((1, 1, Hkv, s_attn),
                         lambda b, li, ln: (li[0], b, 0, 0)),
            pl.BlockSpec((1, 1, Hkv, s_attn, hd),
                         lambda b, li, ln: (li[0], b, 0, 0, 0)),
            pl.BlockSpec((1, 1, Hkv, s_attn),
                         lambda b, li, ln: (li[0], b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, Hkv, G, hd), lambda b, li, ln: (b, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, G, _LANES),
                         lambda b, li, ln: (b, 0, 0, 0)),
            pl.BlockSpec((1, Hkv, G, _LANES),
                         lambda b, li, ln: (b, 0, 0, 0)),
        ),
    )
    sm = hd ** -0.5
    return pl.pallas_call(
        functools.partial(_fd_kernel, sm_scale=sm,
                          blk=min(_BLK, s_attn)),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hkv, G, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, G, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, G, _LANES), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray(li, jnp.int32).reshape(1),
      jnp.asarray(lengths, jnp.int32), q4, k3, ks3, v3, vs3)


def quant_decode_attention(
    q4: jax.Array,
    k3: jax.Array,
    ks3: jax.Array,
    v3: jax.Array,
    vs3: jax.Array,
    lengths: jax.Array,
    layer: jax.Array,
    s_attn: int,
    *,
    interpret: bool | None = None,
):
    """Prefix attention for one decode step over the stacked int8
    cache; returns (acc, m, l) — the unnormalized weighted values and
    per-(head, group) running max / sum for the caller's online-softmax
    merge with the step's local entry.

    ``q4``: (B, Hkv, G, hd). ``k3``/``v3``: (L, B, Hkv, S, hd) int8
    (head-major). ``ks3``/``vs3``: (L, B, Hkv, S) fp32 scales.
    ``lengths``: (B,) valid-prefix lengths. ``s_attn``: static attend
    bound, a multiple of 256 (the engine's bucket step).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # the FULL cache goes in; the BlockSpecs read only the s_attn
    # prefix — slicing here would materialize a copy of exactly the
    # bytes this kernel exists not to copy
    o, m, l = _fd_call(q4, k3, ks3, v3, vs3,
                       lengths, layer, s_attn, interpret)
    return o, m[..., 0], l[..., 0]


def merge_local(o, m, l, lg_l, v_local):
    """Online-softmax merge of the kernel's prefix partials with the
    current step's single local entry (its logit ``lg_l`` (B, Hkv, G)
    and value ``v_local`` (B, Hkv, hd)) → normalized (B, Hkv, G, hd).
    Bit-for-bit the joint softmax over (prefix ‖ local)."""
    m_tot = jnp.maximum(m, lg_l)
    alpha = jnp.exp(m - m_tot)
    beta = jnp.exp(lg_l - m_tot)
    l_tot = l * alpha + beta
    num = o * alpha[..., None] + (
        v_local[:, :, None, :].astype(jnp.float32) * beta[..., None]
    )
    return num / l_tot[..., None]
