"""Pallas TPU kernels for the hot ops.

The reference has no kernel layer (it is a slicing operator, not a compute
framework); this package exists because the flagship workload's
performance ceiling on TPU is set by how well the hot ops map to the
MXU/VMEM hierarchy. XLA fuses most of the model already; the kernels here
cover what it does not schedule optimally — flash attention's online
softmax keeps the (S, S) logits matrix out of HBM entirely.
"""

from instaslice_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
