"""Cluster-controller entry point (reference: ``cmd/controller/main.go:55-168``)."""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslice-controller",
        description="instaslice_tpu cluster controller: watches gated pods, "
        "allocates TPU sub-slices, ungates.",
    )
    from instaslice_tpu.topology.policy import policy_names

    p.add_argument("--namespace", default="instaslice-tpu-system",
                   help="namespace for operator-owned objects")
    p.add_argument("--policy", default="first-fit", choices=policy_names(),
                   help="allocation policy")
    p.add_argument("--metrics-bind-address", default=":8080")
    p.add_argument("--health-probe-bind-address", default=":8081")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--workers", type=int, default=None,
                   help="sharded reconcile workers (default: "
                   "TPUSLICE_RECONCILE_WORKERS or 4; per-key ordering "
                   "is preserved — docs/SCALING.md)")
    p.add_argument("--shard-leases", action="store_true",
                   help="active-active scale-out: each reconcile shard "
                   "holds its own Lease, so multiple controller "
                   "replicas split the shards (docs/SCALING.md)")
    p.add_argument("--kubeconfig", default="")
    p.add_argument("--deletion-grace-seconds", type=float, default=30.0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from instaslice_tpu.cli.runtime import run_controller

    return run_controller(args)


if __name__ == "__main__":
    sys.exit(main())
