"""Cluster-controller entry point (reference: ``cmd/controller/main.go:55-168``)."""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslice-controller",
        description="instaslice_tpu cluster controller: watches gated pods, "
        "allocates TPU sub-slices, ungates.",
    )
    from instaslice_tpu.topology.policy import policy_names

    def policy_arg(value: str) -> str:
        # validate at parse time (clean exit-2 usage error, like the
        # old choices= did) while leaving the default to the env-var
        # resolution in ControllerRunner
        if value not in policy_names():
            raise argparse.ArgumentTypeError(
                f"unknown policy {value!r}; registered: "
                + ", ".join(policy_names())
            )
        return value

    p.add_argument("--namespace", default="instaslice-tpu-system",
                   help="namespace for operator-owned objects")
    p.add_argument("--policy", default=None, type=policy_arg,
                   help="allocation policy (default: the "
                   "TPUSLICE_PLACEMENT_POLICY env var, else first-fit); "
                   "registered: " + ", ".join(policy_names()))
    p.add_argument("--repack", action="store_true",
                   help="run the live-defragmentation loop: migrate "
                   "relocatable slices (drain->teardown->re-grant) when "
                   "a pending profile is blocked only by stranded "
                   "capacity (docs/SCALING.md; opt pods out with the "
                   "no-repack annotation)")
    p.add_argument("--repack-interval", type=float, default=5.0,
                   help="seconds between repacker passes")
    p.add_argument("--repack-max-concurrent", type=int, default=2,
                   help="max in-flight slice migrations")
    p.add_argument("--repack-cooldown", type=float, default=300.0,
                   help="per-pod seconds between migrations (thrash "
                   "brake)")
    p.add_argument("--repack-frag-threshold", type=float, default=None,
                   help="proactive repacking: also plan when a group's "
                   "stranded-capacity fraction (topology/frag.py) "
                   "exceeds this, not only on a starved pod (default: "
                   "TPUSLICE_REPACK_FRAG_THRESHOLD env var, else off)")
    p.add_argument("--metrics-bind-address", default=":8080")
    p.add_argument("--health-probe-bind-address", default=":8081")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--workers", type=int, default=None,
                   help="sharded reconcile workers (default: "
                   "TPUSLICE_RECONCILE_WORKERS or 4; per-key ordering "
                   "is preserved — docs/SCALING.md)")
    p.add_argument("--shard-leases", action="store_true",
                   help="active-active scale-out: each reconcile shard "
                   "holds its own Lease, so multiple controller "
                   "replicas split the shards (docs/SCALING.md)")
    p.add_argument("--kubeconfig", default="")
    p.add_argument("--deletion-grace-seconds", type=float, default=30.0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from instaslice_tpu.cli.runtime import run_controller

    return run_controller(args)


if __name__ == "__main__":
    sys.exit(main())
