"""``tpuslice-train``: the end-to-end training entry point.

Runs inside a granted slice pod (or anywhere, on CPU, for CI): builds
the mesh (single-process, or multi-host from the agent's handoff env),
streams batches from a memory-mapped token dataset
(:mod:`instaslice_tpu.models.data`), executes the sharded train step
(:mod:`instaslice_tpu.models.train` — dp/sp/tp, GQA, MoE, remat,
chunked loss), and checkpoints through
:class:`instaslice_tpu.models.checkpoint.TrainCheckpointer` with
bit-identical resume: batches are a pure function of the step number,
so the restored step counter IS the loader state.

The reference has no training story at all (its samples mount a
notebook onto the slice); this closes the workload loop the way
``tpuslice-serve`` closes the serving loop.

SIGINT saves a final checkpoint and exits cleanly — the claimant-unwind
contract every TPU-touching process in this repo follows.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time

log = logging.getLogger("instaslice_tpu.train")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpuslice-train")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--data", default="",
                     help="token file (.npy / .u16 / .u32 flat stream)")
    src.add_argument("--synthetic", type=int, default=0, metavar="N",
                     help="train on N random tokens (smoke/benchmark "
                          "mode — no dataset needed)")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="micro-batches per optimizer update (activation "
                         "memory scales with batch/accum)")
    ap.add_argument("--grad-clip", type=float, default=1.0,
                    help="global L2 gradient-norm clip (0 disables)")
    ap.add_argument("--warmup-steps", type=int, default=0,
                    help=">0: linear warmup then cosine decay to 10%% "
                         "over --steps")
    ap.add_argument("--seed", type=int, default=0)
    # model
    ap.add_argument("--d-model", type=int, default=2048)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--n-kv-heads", type=int, default=0)
    ap.add_argument("--n-layers", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=8192)
    ap.add_argument("--vocab-size", type=int, default=32000)
    ap.add_argument("--n-experts", type=int, default=0)
    ap.add_argument("--window", type=int, default=0,
                    help="sliding-window attention (0 = full causal)")
    ap.add_argument("--remat", default="none",
                    choices=("none", "dots", "full"))
    ap.add_argument("--ring", action="store_true",
                    help="ring attention over the seq axis (long "
                         "context; requires --sp > 1)")
    # mesh
    ap.add_argument("--from-env", action="store_true",
                    help="multi-host: rendezvous + mesh from the "
                         "agent's handoff env (TPU_* vars)")
    ap.add_argument("--tp", type=int, default=1,
                    help="model-axis size (heads/ffn sharding)")
    ap.add_argument("--sp", type=int, default=1,
                    help="seq-axis size (ring attention)")
    ap.add_argument("--param-dtype", default="float32",
                    choices=["float32", "same"],
                    help="weight storage dtype on TPU (float32 = master "
                         "weights, the mixed-precision recipe; same = "
                         "store in the bf16 compute dtype)")
    ap.add_argument("--lora-rank", type=int, default=0,
                    help=">0: LoRA fine-tuning — train rank-R adapters "
                         "over a frozen base (int8 base = QLoRA); the "
                         "checkpoint then holds adapters only")
    ap.add_argument("--lora-alpha", type=float, default=16.0)
    ap.add_argument("--lora-targets", default="wq,wv",
                    help="comma list of adapted weights "
                         "(wq,wk,wv,wo,w_in,w_out)")
    ap.add_argument("--base-checkpoint", default="",
                    help="LoRA: restore the frozen base params from "
                         "this full-training checkpoint dir (default: "
                         "fresh init — smoke tests only)")
    ap.add_argument("--quantize-base", action="store_true",
                    help="LoRA: int8-quantize the frozen base before "
                         "training (QLoRA — ~half the base-weight HBM)")
    ap.add_argument("--zero1", action="store_true",
                    help="shard Adam moments over the data axis (ZeRO "
                         "stage 1): ~2/3 of optimizer+param state "
                         "divided by dp size, identical step math")
    # checkpoint / logging
    ap.add_argument("--checkpoint", default="",
                    help="orbax checkpoint dir (resume if it has one)")
    ap.add_argument("--save-every", type=int, default=50)
    ap.add_argument("--max-keep", type=int, default=3)
    ap.add_argument("--log-every", type=int, default=10)
    return ap


def _build_mesh(args):
    import jax

    if args.from_env:
        from instaslice_tpu.parallel.meshenv import (
            SliceTopology,
            initialize_distributed,
            slice_mesh,
        )

        topo = SliceTopology.from_env()
        initialize_distributed(topo)
        devs = jax.devices()[: topo.num_chips]
        return slice_mesh(
            axes=("data", "seq", "model"),
            axis_sizes=(-1, args.sp, args.tp),
            devices=devs, topo=topo,
        )
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    n = len(devs)
    if n % (args.tp * args.sp):
        raise SystemExit(
            f"{n} devices not divisible by tp={args.tp} * sp={args.sp}"
        )
    dp = n // (args.tp * args.sp)
    return Mesh(
        np.array(devs).reshape(dp, args.sp, args.tp),
        ("data", "seq", "model"),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from instaslice_tpu.utils.tpulock import TpuBusyError, claim_or_force_cpu

    try:
        claim = claim_or_force_cpu()
    except TpuBusyError as e:
        log.error("%s", e)
        return 3

    import jax
    import jax.numpy as jnp
    import numpy as np

    from instaslice_tpu.models.checkpoint import (
        TrainCheckpointer,
        abstract_train_state,
    )
    from instaslice_tpu.models.data import (
        HostShardedTokens,
        Prefetcher,
        TokenDataset,
        write_token_file,
    )
    from instaslice_tpu.models.lm import ModelConfig, TpuLM, batch_spec
    from instaslice_tpu.models.train import make_train_step

    try:
        mesh = _build_mesh(args)
        dp = mesh.shape["data"]
        if args.global_batch % dp:
            raise SystemExit(
                f"--global-batch {args.global_batch} must be divisible "
                f"by the data-parallel axis ({dp} = {len(jax.devices())} "
                f"devices / tp {args.tp} / sp {args.sp})"
            )
        if args.ring and (args.seq_len + 1) % max(args.sp, 1):
            # dataset rows are seq_len+1 wide (inputs + shifted target)
            # and ring shards that dim over the seq axis
            raise SystemExit(
                f"--ring shards (seq_len + 1) = {args.seq_len + 1} over "
                f"sp={args.sp}, which does not divide; use a seq-len "
                f"of (multiple of {args.sp}) - 1, e.g. "
                f"{args.sp * ((args.seq_len + 1) // args.sp) - 1}"
            )
        on_tpu = jax.default_backend() == "tpu"
        cfg = ModelConfig(
            vocab_size=args.vocab_size, d_model=args.d_model,
            n_heads=args.n_heads, n_kv_heads=args.n_kv_heads,
            n_layers=args.n_layers, d_ff=args.d_ff,
            max_seq_len=args.seq_len + 1,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32,
            # mixed-precision training default: bf16 compute on the
            # MXU, fp32 master weights so sub-bf16-ulp Adam updates
            # are never lost (--param-dtype same opts out when memory
            # is tighter than late-training convergence)
            param_dtype=(jnp.float32 if args.param_dtype == "float32"
                         else None) if on_tpu else None,
            ring_attention=args.ring, n_experts=args.n_experts,
            window=args.window,
            remat=args.remat != "none",
            remat_policy="dots" if args.remat == "dots" else "full",
        )
        model = TpuLM(cfg)
        if args.lora_rank:
            from jax.sharding import NamedSharding

            from instaslice_tpu.models.lm import param_specs
            from instaslice_tpu.models.lora import (
                LoraConfig,
                make_lora_train_step,
            )
            from jax.sharding import PartitionSpec as P

            if args.zero1:
                raise SystemExit(
                    "--zero1 has nothing to shard in a LoRA run (the "
                    "adapter moments are ~0.1% of the base); remove it"
                )
            lcfg = LoraConfig(
                rank=args.lora_rank, alpha=args.lora_alpha,
                targets=tuple(
                    t for t in args.lora_targets.split(",") if t
                ),
            )
            if args.base_checkpoint:
                # the restore skeleton must match the base run's
                # opt_state STRUCTURE, which depends on the optimizer
                # flags (clip adds a transform state, warmup adds a
                # schedule count): pass the same flags the base was
                # trained with
                full_init, _ = make_train_step(
                    model, mesh,
                    zero1=args.zero1,
                    grad_clip=args.grad_clip,
                    warmup_steps=args.warmup_steps,
                    decay_steps=args.steps if args.warmup_steps else 0,
                )
                with TrainCheckpointer(
                    args.base_checkpoint, max_to_keep=1,
                ) as bc:
                    restored = bc.restore(
                        abstract_train_state(full_init)
                    )
                if restored is None:
                    raise SystemExit(
                        f"--base-checkpoint {args.base_checkpoint} has "
                        "no restorable checkpoint"
                    )
                base_params = restored.params
                # the restored Adam moments (2x params) must not stay
                # referenced for the whole fine-tune — that would undo
                # the LoRA memory win. (They do transiently exist at
                # restore; a params-only partial restore would avoid
                # even that peak.)
                del restored
            else:
                psh = jax.tree.map(
                    lambda s: NamedSharding(mesh, s), param_specs(cfg),
                    is_leaf=lambda x: isinstance(x, P),
                )
                base_params = jax.jit(
                    model.init, out_shardings=psh,
                )(jax.random.key(args.seed))
            if args.quantize_base:
                from instaslice_tpu.models.quant import quantize_params

                base_params = quantize_params(base_params)
            init_fn, step_fn = make_lora_train_step(
                model, mesh, base_params, lcfg,
                learning_rate=args.lr, grad_clip=args.grad_clip,
                grad_accum=args.grad_accum,
                warmup_steps=args.warmup_steps,
                decay_steps=args.steps if args.warmup_steps else 0,
            )
        else:
            init_fn, step_fn = make_train_step(
                model, mesh,
                learning_rate=args.lr,
                zero1=args.zero1,
                grad_accum=args.grad_accum,
                grad_clip=args.grad_clip,
                warmup_steps=args.warmup_steps,
                decay_steps=args.steps if args.warmup_steps else 0,
            )

        data_path = args.data
        if args.synthetic:
            import os
            import tempfile

            # per-process file: two concurrent synthetic runs must not
            # rewrite a corpus the other has live-mmap'd (silent wrong
            # data, or SIGBUS if the file shrinks under the mapping)
            data_path = os.path.join(
                tempfile.gettempdir(),
                f"tpuslice-synthetic-{args.seed}-{os.getpid()}.u16",
            )
            rng = np.random.default_rng(args.seed)
            write_token_file(
                data_path,
                rng.integers(1, min(cfg.vocab_size, 65535),
                             size=args.synthetic),
            )
            log.info("synthetic corpus: %d tokens at %s",
                     args.synthetic, data_path)
        ds = TokenDataset(data_path, args.seq_len, seed=args.seed)
        loader = HostShardedTokens(
            ds, mesh, args.global_batch, spec=batch_spec(cfg)
        )

        ckpt = None
        state = None
        if args.checkpoint:
            ckpt = TrainCheckpointer(
                args.checkpoint, max_to_keep=args.max_keep,
                save_interval_steps=1,
            )
            restored = ckpt.restore(abstract_train_state(init_fn))
            if restored is not None:
                state = restored
                log.info("resumed from step %d", int(state.step))
        if state is None:
            state = init_fn(jax.random.key(args.seed))

        start = int(state.step)
        prefetch = Prefetcher(loader.batch_for_step, start_step=start)
        t0 = time.monotonic()
        tokens_done = 0
        last_loss = float("nan")
        try:
            for step, batch in prefetch:
                if step >= args.steps:
                    break
                state, loss = step_fn(state, batch)
                tokens_done += args.global_batch * args.seq_len
                if (step + 1) % args.log_every == 0 or \
                        step + 1 == args.steps:
                    last_loss = float(loss)   # sync point
                    dt = time.monotonic() - t0
                    log.info(
                        "step %d loss %.4f  %.0f tok/s",
                        step + 1, last_loss,
                        tokens_done / max(dt, 1e-9),
                    )
                if ckpt is not None and (step + 1) % args.save_every == 0:
                    ckpt.save(state)
        except KeyboardInterrupt:
            log.info("interrupted at step %d; saving", int(state.step))
        finally:
            prefetch.close()
            if ckpt is not None:
                ckpt.save(state)
                ckpt.close()
        wall = time.monotonic() - t0
        print(json.dumps({
            "metric": "train_tokens_per_sec",
            "value": round(tokens_done / max(wall, 1e-9), 1),
            "unit": "tokens/s",
            "steps": int(state.step),
            # None (JSON null), not NaN: a resumed run that was already
            # at --steps does zero work, and bare NaN is invalid JSON
            "final_loss": (round(last_loss, 4)
                           if last_loss == last_loss else None),
            "params_m": round(sum(
                int(np.prod(l.shape))
                for l in jax.tree.leaves(state.params)
            ) / 1e6, 1),
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
            "backend": jax.default_backend(),
        }))
        return 0
    finally:
        if claim is not None:
            claim.release()


if __name__ == "__main__":
    sys.exit(main())
