"""One-process demo of the whole operator: ``python -m instaslice_tpu.cli.demo``.

Boots a :class:`SimCluster` (fake kube API + controller + node agents +
fake TPU backends + scheduler emulator — or the same over real HTTP with
``--transport http``), then walks the reference's README demo flow
(`/root/reference/README.md:190-300` shows the same story via
``kubectl``/``nvidia-smi`` transcripts) without needing a cluster:

1. submit a gated pod requesting a 2x2 profile,
2. watch allocation → realization → handoff ConfigMap → ungate → Running,
3. print the libtpu env the pod would consume,
4. delete the pod and watch the slice tear down.

Useful as a smoke test of an installed package and as executable
documentation of the grant lifecycle.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="instaslice-tpu demo")
    ap.add_argument("--profile", default="v5e-2x2")
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--transport", choices=("inproc", "http"),
                    default="inproc")
    ap.add_argument("--keep", action="store_true",
                    help="skip the teardown half")
    args = ap.parse_args(argv)

    from instaslice_tpu.sim import SimCluster

    def say(msg):
        print(f"[demo] {msg}")

    say(f"booting {args.nodes}-node v5e sim cluster "
        f"(transport={args.transport})")
    with SimCluster(n_nodes=args.nodes, generation="v5e",
                    deletion_grace_seconds=0.5,
                    transport=args.transport) as c:
        name = "demo-pod"
        say(f"submitting gated pod {name!r} requesting {args.profile}")
        t0 = time.monotonic()
        c.submit(name, profile=args.profile)
        if not c.wait_phase(name, "Running", timeout=60):
            say(f"FAILED: pod stuck in {c.pod_phase(name)}")
            return 1
        dt = time.monotonic() - t0
        say(f"pod Running after {dt:.2f}s "
            "(gate→place→realize→handoff→ungate→bind)")

        allocs = c.allocations()
        for alloc in allocs.values():
            say(f"allocation: profile={alloc['profile']} "
                f"box={alloc['box']} status={alloc['status']} "
                f"nodes={sorted(alloc['parts'])}")
        cm = c.configmap(name)
        say("handoff env (what the pod's envFrom sees):")
        for k in sorted(cm["data"]):
            if k.startswith("TPU_"):
                print(f"    {k}={cm['data'][k]}")

        if args.keep:
            say("--keep: leaving the slice granted")
            return 0

        say(f"deleting {name!r} (grace 0.5s)")
        t0 = time.monotonic()
        c.delete_pod(name)
        if not c.wait_gone(name, timeout=60):
            say("FAILED: pod never finalized")
            return 1
        deadline = time.monotonic() + 30
        while c.allocations() and time.monotonic() < deadline:
            # CLI observer poll, deadline-bounded; ^C interrupts sleep
            time.sleep(0.05)  # slicelint: disable=sleep-in-loop
        if c.allocations():
            say(f"FAILED: allocation not erased: {c.allocations()}")
            return 1
        say(f"teardown complete after {time.monotonic() - t0:.2f}s "
            "(finalizer → agent release → CR erase)")
        say("demo OK")
        print(json.dumps({"demo": "ok", "grant_seconds": round(dt, 3)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
