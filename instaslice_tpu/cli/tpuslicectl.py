"""``tpuslice`` operator CLI: inspect catalogs, simulate placement, demo."""

from __future__ import annotations

import argparse
import json
import sys


def _serve_bench(args) -> int:
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.serving import ServingEngine

    on_tpu = jax.default_backend() == "tpu"
    cfg = ModelConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        window=args.window,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        remat=False,
    )
    model = TpuLM(cfg)
    params = model.init(jax.random.key(0))
    kw = {}
    if args.quantize or args.spec:
        from instaslice_tpu.models.quant import quantize_params

        qparams = quantize_params(params)
    if args.quantize:
        params, kw["kv_quant"] = qparams, True
    if args.spec:
        kw.update(draft_model=model, draft_params=qparams, spec_k=4)
    eng = ServingEngine(
        model, params, max_batch=args.batch, max_len=args.max_len,
        prefill_len=args.prefill_len, **kw,
    )
    out = {
        "metric": "serve_decode_tokens_per_sec",
        "unit": "tokens/s",
        "backend": jax.default_backend(),
        "batch": args.batch,
        "quantized": bool(args.quantize),
        "speculative": bool(args.spec),
        "model": {
            "dModel": args.d_model, "nLayers": args.n_layers,
            "nHeads": args.n_heads, "nKvHeads": args.n_kv_heads,
            "dFF": args.d_ff, "window": args.window,
        },
    }
    if args.spec:
        tput, per_round = eng.spec_throughput(rounds=args.steps)
        out["value"] = round(tput, 1)
        out["spec_tokens_per_round"] = round(per_round, 2)
    else:
        out["value"] = round(eng.throughput(n_steps=args.steps), 1)
    print(json.dumps(out))
    return 0


def _trace_summary(p, args) -> int:
    """``trace-summary``: one span pipeline, two sources — an offline
    ``TPUSLICE_TRACE_FILE`` JSONL dump, or a live server's in-memory
    ring over ``GET /v1/debug/trace``. Default output is per-span-name
    p50/p95/max rows; ``--slowest N`` adds the N slowest trace roots;
    ``--trace ID`` dumps one trace's spans in start order."""
    from instaslice_tpu.utils.trace import summarize_durations

    if bool(args.file) == bool(args.url):
        p.error("trace-summary needs a JSONL file OR --url (not both)")

    if args.url:
        import urllib.parse
        import urllib.request

        base = args.url.rstrip("/") + "/v1/debug/trace"
        query = {}
        if args.trace:
            query["trace_id"] = args.trace
        if args.slowest:
            query["n"] = str(args.slowest)
        if query:
            base += "?" + urllib.parse.urlencode(query)
        try:
            with urllib.request.urlopen(base, timeout=10) as r:
                out = json.loads(r.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI: message, not trace
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 1
        if args.trace:
            for span in out.get("spans", []):
                print(json.dumps(span))
            return 0
        for name, row in out.get("summary", {}).items():
            print(json.dumps({"name": name, **row}))
        if args.slowest:
            for span in out.get("slowest", [])[: args.slowest]:
                print(json.dumps(span))
        return 0

    spans = []
    with open(args.file) as f:
        for line in f:
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    if args.trace:
        mine = [s for s in spans if s.get("traceId") == args.trace]
        for span in sorted(mine, key=lambda s: s.get("start", 0.0)):
            print(json.dumps(span))
        return 0 if mine else 1
    by: dict = {}
    for rec in spans:
        by.setdefault(rec["name"], []).append(rec["durationMs"])
    for name, row in summarize_durations(by).items():
        print(json.dumps({"name": name, **row}))
    if args.slowest:
        roots = [s for s in spans if not s.get("parentId")]
        roots.sort(key=lambda s: -s["durationMs"])
        for span in roots[: args.slowest]:
            print(json.dumps(span))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tpuslice", description="instaslice_tpu operator CLI"
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    cat = sub.add_parser("catalog", help="print the profile catalog")
    cat.add_argument("generation", help="TPU generation, e.g. v5e")
    cat.add_argument("--max-chips", type=int, default=None)

    place = sub.add_parser("plan", help="simulate placing profiles on a mesh")
    place.add_argument("generation")
    place.add_argument("profiles", nargs="+", help="e.g. v5e-2x2 v5e-1x1")
    place.add_argument("--hosts", type=int, default=1)
    place.add_argument("--policy", default="best-fit")

    tr = sub.add_parser(
        "trace-summary",
        help="summarize spans from a TPUSLICE_TRACE_FILE JSONL or a "
        "live server's GET /v1/debug/trace (per-span p50/p95/max, "
        "slowest traces, single-trace drill-down)",
    )
    tr.add_argument("file", nargs="?", default="",
                    help="trace JSONL path (or use --url)")
    tr.add_argument("--url", default="",
                    help="live tpuslice-serve base url (e.g. "
                         "http://127.0.0.1:8000): read the in-memory "
                         "ring over GET /v1/debug/trace instead of a "
                         "file")
    tr.add_argument("--trace", default="", metavar="TRACE_ID",
                    help="dump every span of ONE trace (start order) "
                         "— the id an X-Trace-Id response header or a "
                         "slowest-traces row points at")
    tr.add_argument("--slowest", type=int, default=0, metavar="N",
                    help="also print the N slowest trace roots "
                         "(name, traceId, durationMs)")

    st = sub.add_parser(
        "status",
        help="cluster slice status: per-node chips, health, allocations "
        "(the `kubectl get` + `nvidia-smi` half of the reference's demo "
        "transcript, from the CRs)",
    )
    st.add_argument("--kubeconfig", default="")
    st.add_argument("--namespace", default="instaslice-tpu-system")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")

    sb = sub.add_parser(
        "serve-bench",
        help="decode tokens/sec of the serving engine on this host's "
        "accelerator (BASELINE secondary metric: divide by chip count)",
    )
    sb.add_argument("--d-model", type=int, default=512)
    sb.add_argument("--n-layers", type=int, default=4)
    sb.add_argument("--n-heads", type=int, default=8)
    sb.add_argument("--n-kv-heads", type=int, default=0,
                    help="grouped-query attention KV heads (0 = MHA)")
    sb.add_argument("--d-ff", type=int, default=2048)
    sb.add_argument("--vocab", type=int, default=32000)
    sb.add_argument("--batch", type=int, default=8)
    sb.add_argument("--max-len", type=int, default=256)
    sb.add_argument("--prefill-len", type=int, default=16)
    sb.add_argument("--steps", type=int, default=30)
    sb.add_argument("--window", type=int, default=0,
                    help="sliding-window attention (0 = full causal)")
    sb.add_argument("--quantize", action="store_true",
                    help="int8 weights + int8 KV cache")
    sb.add_argument("--spec", action="store_true",
                    help="speculative decoding (int8 self-draft, "
                         "lossless greedy): reports tokens/sec and "
                         "accepted tokens per verify round")

    args = p.parse_args(argv)

    if args.cmd == "serve-bench" and args.prefill_len > args.max_len:
        p.error(
            f"--prefill-len {args.prefill_len} must be <= --max-len "
            f"{args.max_len}"
        )
    if args.cmd == "serve-bench" and args.quantize and args.spec:
        p.error(
            "--quantize with --spec would make the draft IDENTICAL to "
            "the int8 target (guaranteed full acceptance, pure "
            "overhead); --spec already uses an int8 draft against the "
            "full-precision target — pick one"
        )

    if args.cmd == "serve-bench":
        from instaslice_tpu.utils.tpulock import (
            TpuBusyError,
            claim_or_force_cpu,
        )

        try:
            # one-claimant rule: this subcommand initializes the host's
            # accelerator backend, so it must hold the host-wide TPU
            # claim (or pin CPU in-process when env-forced to cpu)
            claim = claim_or_force_cpu()
        except TpuBusyError as e:
            print(json.dumps({"error": str(e)}))
            return 3
        try:
            return _serve_bench(args)
        finally:
            if claim is not None:
                claim.release()



    if args.cmd == "status":
        from instaslice_tpu import KIND
        from instaslice_tpu.api.types import TpuSlice
        from instaslice_tpu.kube.real import build_client

        client = build_client(args.kubeconfig)
        nodes = []
        # multi-host allocations are fanned out to every participating
        # node's CR (controller/reconciler._write_allocation): merge by
        # allocation id so one slice is reported ONCE, with the union of
        # realized parts (the controller's own merged-view semantics)
        slices: dict = {}
        for m in sorted(
            client.list(KIND, namespace=args.namespace),
            key=lambda m: m["metadata"]["name"],
        ):
            ts = TpuSlice.from_manifest(m)
            nodes.append({
                "node": ts.name,
                "generation": ts.spec.generation,
                "chips": len(ts.spec.chips),
                "unhealthyChips": sorted(ts.status.unhealthy_chips),
                "prepared": len(ts.spec.prepared),
            })
            for aid, a in sorted(ts.spec.allocations.items()):
                s = slices.setdefault(aid, {
                    "id": aid,
                    "profile": a.profile,
                    "box": a.box,
                    "status": a.status.value,
                    "pods": sorted(p.pod_name for p in a.pods),
                    "nodes": sorted(a.parts),
                    "parts": len(a.parts),
                    "realizedOn": set(),
                })
                s["realizedOn"].update(a.realized_on)
        for s in slices.values():
            s["realizedOn"] = sorted(s["realizedOn"])
        out = {"nodes": nodes, "slices": sorted(
            slices.values(), key=lambda s: s["id"]
        )}
        if args.as_json:
            print(json.dumps(out))
            return 0
        if not nodes:
            print(f"no {KIND} objects in namespace {args.namespace}")
            return 0
        for n in nodes:
            bad = (f" unhealthy={n['unhealthyChips']}"
                   if n["unhealthyChips"] else "")
            print(f"{n['node']}: {n['generation']} chips={n['chips']}"
                  f" prepared={n['prepared']}{bad}")
        if out["slices"]:
            print("slices:")
        for s in out["slices"]:
            print(f"  {s['id'][:20]:<20} {s['profile']:<10} "
                  f"{s['box']:<14} {s['status']:<9} "
                  f"pods={','.join(s['pods'])} "
                  f"nodes={','.join(s['nodes'])} "
                  f"realized={len(s['realizedOn'])}/{s['parts']}")
        return 0

    if args.cmd == "trace-summary":
        return _trace_summary(p, args)

    if args.cmd == "catalog":
        from instaslice_tpu.topology import profile_catalog

        for prof in profile_catalog(args.generation, args.max_chips):
            print(json.dumps({"name": prof.name, **prof.attributes()}))
        return 0

    if args.cmd == "plan":
        from instaslice_tpu.topology import (
            NodeGrid,
            Occupancy,
            TorusGroup,
            get_policy,
            parse_profile_name,
        )
        from instaslice_tpu.topology.grid import get_generation

        gen = get_generation(args.generation)
        hb = gen.host_bounds
        hosts = {
            f"host-{i}": NodeGrid(gen, host_offset=(i * hb[0], 0, 0))
            for i in range(args.hosts)
        }
        group = TorusGroup(
            "plan", gen, (hb[0] * args.hosts, hb[1], hb[2]), hosts
        )
        occ = Occupancy(group)
        pol = get_policy(args.policy)
        ok = True
        for i, name in enumerate(args.profiles):
            pl = pol.choose(group, parse_profile_name(name), occ)
            if pl is None:
                print(json.dumps({"request": name, "placed": False}))
                ok = False
                continue
            occ.occupy(pl.box, owner=str(i))
            print(
                json.dumps(
                    {
                        "request": name,
                        "placed": True,
                        "box": pl.box.key(),
                        "hosts": {
                            pt.node_name: pt.local_chip_ids(hb)
                            for pt in pl.parts
                        },
                    }
                )
            )
        return 0 if ok else 1

    return 2


if __name__ == "__main__":
    sys.exit(main())
