"""``tpuslice`` operator CLI: inspect catalogs, simulate placement, demo,
and read the observability planes (traces, flight-recorder events, the
per-pod decision timeline)."""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import threading


def _serve_bench(args) -> int:
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.serving import ServingEngine

    on_tpu = jax.default_backend() == "tpu"
    cfg = ModelConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        window=args.window,
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        remat=False,
    )
    model = TpuLM(cfg)
    params = model.init(jax.random.key(0))
    kw = {}
    if args.quantize or args.spec:
        from instaslice_tpu.models.quant import quantize_params

        qparams = quantize_params(params)
    if args.quantize:
        params, kw["kv_quant"] = qparams, True
    if args.spec:
        kw.update(draft_model=model, draft_params=qparams, spec_k=4)
    eng = ServingEngine(
        model, params, max_batch=args.batch, max_len=args.max_len,
        prefill_len=args.prefill_len, **kw,
    )
    out = {
        "metric": "serve_decode_tokens_per_sec",
        "unit": "tokens/s",
        "backend": jax.default_backend(),
        "batch": args.batch,
        "quantized": bool(args.quantize),
        "speculative": bool(args.spec),
        "model": {
            "dModel": args.d_model, "nLayers": args.n_layers,
            "nHeads": args.n_heads, "nKvHeads": args.n_kv_heads,
            "dFF": args.d_ff, "window": args.window,
        },
    }
    if args.spec:
        tput, per_round = eng.spec_throughput(rounds=args.steps)
        out["value"] = round(tput, 1)
        out["spec_tokens_per_round"] = round(per_round, 2)
    else:
        out["value"] = round(eng.throughput(n_steps=args.steps), 1)
    print(json.dumps(out))
    return 0


def _trace_summary(p, args) -> int:
    """``trace-summary``: one span pipeline, two sources — an offline
    ``TPUSLICE_TRACE_FILE`` JSONL dump, or a live server's in-memory
    ring over ``GET /v1/debug/trace``. Default output is per-span-name
    p50/p95/max rows; ``--slowest N`` adds the N slowest trace roots;
    ``--trace ID`` dumps one trace's spans in start order."""
    from instaslice_tpu.utils.trace import summarize_durations

    if bool(args.file) == bool(args.url):
        p.error("trace-summary needs a JSONL file OR --url (not both)")

    if args.url:
        import urllib.parse
        import urllib.request

        base = args.url.rstrip("/") + "/v1/debug/trace"
        query = {}
        if args.trace:
            query["trace_id"] = args.trace
        if args.slowest:
            query["n"] = str(args.slowest)
        if query:
            base += "?" + urllib.parse.urlencode(query)
        try:
            with urllib.request.urlopen(base, timeout=10) as r:
                out = json.loads(r.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI: message, not trace
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 1
        if args.trace:
            for span in out.get("spans", []):
                print(json.dumps(span))
            return 0
        for name, row in out.get("summary", {}).items():
            print(json.dumps({"name": name, **row}))
        if args.slowest:
            for span in out.get("slowest", [])[: args.slowest]:
                print(json.dumps(span))
        return 0

    spans = []
    with open(args.file) as f:
        for line in f:
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    if args.trace:
        mine = [s for s in spans if s.get("traceId") == args.trace]
        for span in sorted(mine, key=lambda s: s.get("start", 0.0)):
            print(json.dumps(span))
        return 0 if mine else 1
    by: dict = {}
    for rec in spans:
        by.setdefault(rec["name"], []).append(rec["durationMs"])
    for name, row in summarize_durations(by).items():
        print(json.dumps({"name": name, **row}))
    if args.slowest:
        roots = [s for s in spans if not s.get("parentId")]
        roots.sort(key=lambda s: -s["durationMs"])
        for span in roots[: args.slowest]:
            print(json.dumps(span))
    return 0


def _get_json(url: str, timeout: float = 10):
    """GET url → parsed JSON; (None, error-string) style return:
    ``(payload, "")`` on success, ``(None, message)`` on any failure."""
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode()), ""
    except Exception as e:  # slicelint: disable=broad-except
        # CLI surface: the message IS the report (printed by callers)
        return None, f"{type(e).__name__}: {e}"


def _profile_cmd(args) -> int:
    """``profile``: the continuous profiler's export surface. Without
    ``--out``: per-segment p50/p95 summary rows (one JSON line each,
    the trace-summary idiom). With ``--out trace.json``: fetch the
    round records + timeline events (``GET /v1/debug/profile``) and
    the tracer's recent spans (``GET /v1/debug/trace``), interleave
    them into Chrome trace-event JSON (obs/profiler.py
    ``chrome_trace``), and write the file — open it in Perfetto or
    ``chrome://tracing``."""
    from instaslice_tpu.obs.profiler import chrome_trace

    base = args.url.rstrip("/")
    profile, err = _get_json(f"{base}/v1/debug/profile?n={args.last}")
    if profile is None:
        print(json.dumps({"error": err}))
        return 1
    if not args.out:
        print(json.dumps({
            "armed": profile.get("armed"),
            "rounds": profile.get("rounds"),
            "events": profile.get("events"),
            "compileWallMs": profile.get("compileWallMs"),
        }))
        for name, row in sorted(
            (profile.get("segments") or {}).items()
        ):
            print(json.dumps({"segment": name, **row}))
        for c in profile.get("compiles") or []:
            print(json.dumps({"compile": c}))
        return 0
    # spans ride along on the same timeline; a trace-less component
    # (or a scrape error) degrades to rounds + events only
    trace, _terr = _get_json(f"{base}/v1/debug/trace?n={args.last}")
    spans = (trace or {}).get("recent") or []
    doc = chrome_trace(
        rounds=profile.get("recent") or [],
        events=profile.get("recentEvents") or [],
        spans=spans,
    )
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(json.dumps({
        "out": args.out,
        "traceEvents": len(doc["traceEvents"]),
        "rounds": len(profile.get("recent") or []),
        "events": len(profile.get("recentEvents") or []),
        "spans": len(spans),
    }))
    return 0


def render_waterfall(w: dict) -> str:
    """ASCII latency waterfall: one bar row per stage on a shared
    [0, totalMs] axis, then the journal markers."""
    width = 40
    total = max(float(w.get("totalMs") or 0.0), 0.001)
    lines = [
        f"request {w['rid']}  trace={w['traceId']}  "
        f"outcome={w['outcome'] or '?'}  total={w['totalMs']}ms  "
        f"preemptions={w['preemptions']}"
    ]
    for s in w.get("stages", []):
        start = float(s["startMs"])
        dur = float(s["durationMs"])
        left = min(width, int(round(start / total * width)))
        span = max(1, int(round(dur / total * width)))
        span = min(span, width - left) or 1
        bar = " " * left + "█" * span
        lines.append(
            f"  {s['stage']:<14} {bar:<{width}}  "
            f"{start:>9.2f}ms +{dur:.2f}ms"
        )
    for m in w.get("markers", []):
        lines.append(
            f"  ◆ {m['atMs']:>9.2f}ms  {m['reason']}: {m['message']}"
        )
    return "\n".join(lines)


def _waterfall_cmd(args) -> int:
    """``waterfall``: one request's queue→admission→prefill→rounds→
    (preempt/resume)→finish timeline, stitched server-side from round
    records + journal + trace (``GET /v1/debug/profile?rid=...``)."""
    base = args.url.rstrip("/")
    import urllib.parse

    w, err = _get_json(
        f"{base}/v1/debug/profile?"
        + urllib.parse.urlencode({"rid": args.rid})
    )
    if w is None:
        print(json.dumps({"error": err}))
        return 1
    if w.get("error"):
        print(json.dumps(w))
        return 1
    if args.as_json:
        print(json.dumps(w))
    else:
        print(render_waterfall(w))
    return 0


def _parse_jsonl_line(line: str):
    """One parsed JSONL record, or None for blank/malformed lines — a
    live, half-written tail must never crash a reader. The ONE
    malformed-line policy for every JSONL consumer in this CLI."""
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def _read_jsonl(path: str) -> list:
    """Parsed records from a JSONL file ([] when absent)."""
    out = []
    if not path or not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            rec = _parse_jsonl_line(line)
            if rec is not None:
                out.append(rec)
    return out


def _event_matches(rec: dict, args) -> bool:
    if args.reason and rec.get("reason") != args.reason:
        return False
    if args.object and rec.get("objectRef") != args.object:
        return False
    if args.trace and rec.get("traceId") != args.trace:
        return False
    if args.component and rec.get("component") != args.component:
        return False
    return True


def _events_cmd(p, args) -> int:
    """``events``: the flight recorder, two sources — an offline
    ``TPUSLICE_EVENT_FILE`` JSONL dump, or a live component's
    ``GET /v1/debug/events`` (serving plane or operator probe plane).
    One JSON line per event; ``--follow`` tails the source."""
    if bool(args.file) == bool(args.url):
        p.error("events needs a JSONL file OR --url (not both)")
    pacer = threading.Event()  # interruptible nap (Ctrl-C ends follow)

    if args.url:
        import urllib.parse
        import urllib.request

        base = args.url.rstrip("/") + "/v1/debug/events"
        since = 0
        first = True
        while True:
            # -n bounds only the FIRST (historical) batch, like file
            # mode; follow-up polls fetch everything past since_seq so
            # a burst bigger than n is never silently dropped
            query = {"n": str((args.last or 10000) if first else 100000)}
            first = False
            if args.reason:
                query["reason"] = args.reason
            if args.object:
                query["object"] = args.object
            if args.trace:
                query["trace_id"] = args.trace
            if args.component:
                query["component"] = args.component
            if since:
                query["since_seq"] = str(since)
            url = base + "?" + urllib.parse.urlencode(query)
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    out = json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 - CLI: message, not trace
                print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
                return 1
            for rec in out.get("events", []):
                print(json.dumps(rec))
                since = max(since, int(rec.get("seq", 0)))
            if not args.follow:
                return 0
            pacer.wait(1.0)

    if not os.path.exists(args.file):
        # match URL mode's clean one-line failure, not a traceback
        print(json.dumps({"error": f"no such file: {args.file}"}))
        return 1
    with open(args.file) as f:
        # historical batch first (honoring -n like the other modes),
        # then — under --follow — tail from the current offset
        recs = [rec for rec in map(_parse_jsonl_line, f)
                if rec is not None and _event_matches(rec, args)]
        for rec in recs[-args.last:] if args.last else recs:
            print(json.dumps(rec), flush=True)
        if not args.follow:
            return 0
        while True:
            line = f.readline()
            if not line:
                pacer.wait(0.25)
                continue
            rec = _parse_jsonl_line(line)
            if rec is not None and _event_matches(rec, args):
                print(json.dumps(rec), flush=True)


def _fleet_cmd(args) -> int:
    """``fleet``: one-shot (or ``--follow``) view of a
    ``tpuslice-telemetry`` aggregator. Rollup mode prints the
    ``/v1/fleet`` snapshot as one JSON object per poll; ``--trace``
    mode prints the stitched cross-process timeline for one trace id
    (``/v1/fleet/trace``)."""
    import urllib.parse
    import urllib.request

    base = args.url.rstrip("/")
    if args.trace:
        path = "/v1/fleet/trace?" + urllib.parse.urlencode(
            {"trace_id": args.trace}
        )
    else:
        path = "/v1/fleet"
    pacer = threading.Event()  # interruptible nap (Ctrl-C ends follow)
    while True:
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                out = json.loads(r.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI: message, not trace
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 1
        print(json.dumps(out), flush=True)
        if not args.follow:
            return 0
        pacer.wait(max(0.1, args.interval))


def describe_pod(client, name: str, namespace: str = "default",
                 operator_namespace: str = "instaslice-tpu-system",
                 events_path: str = "", trace_path: str = "") -> dict:
    """Stitch one pod's control-plane history into a single timeline:
    the Kubernetes Events mirrored onto it, the allocation's persisted
    audit trail (CR ``transitions``), the journal JSONL (optional), and
    the grant trace's spans (optional). The data behind ``tpuslice
    describe pod`` — factored for tools/validate_events.py and tests."""
    from instaslice_tpu import KIND
    from instaslice_tpu.api.constants import (
        ERROR_ANNOTATION,
        TRANSITION_REASONS,
        UNHEALTHY_ANNOTATION,
    )
    from instaslice_tpu.api.types import TpuSlice
    from instaslice_tpu.kube.client import ApiError
    from instaslice_tpu.utils.timeutil import parse_timestamp

    info: dict = {
        "pod": name, "namespace": namespace, "phase": "Gone",
        "gated": False, "gates": [], "error": "", "unhealthy": "",
        "allocation": None, "traceId": "", "timeline": [],
    }
    try:
        pod = client.get("Pod", namespace, name)
    except ApiError:
        pod = None
    if pod is not None:
        md = pod.get("metadata", {})
        ann = md.get("annotations") or {}
        gates = [g.get("name", "")
                 for g in pod.get("spec", {}).get("schedulingGates") or []]
        info.update(
            phase=pod.get("status", {}).get("phase", ""),
            gated=bool(gates), gates=gates,
            error=ann.get(ERROR_ANNOTATION, ""),
            unhealthy=ann.get(UNHEALTHY_ANNOTATION, ""),
        )

    timeline: list = []
    alloc_ref = ""
    trace_id = ""
    seen_transitions: set = set()
    try:
        crs = client.list(KIND, namespace=operator_namespace)
    except ApiError:
        crs = []
    for m in crs:
        ts_obj = TpuSlice.from_manifest(m)
        for a in ts_obj.spec.allocations.values():
            if not any(p.pod_name == name and p.namespace == namespace
                       for p in a.pods):
                continue
            if info["allocation"] is None:
                info["allocation"] = {
                    "id": a.alloc_id, "profile": a.profile,
                    "box": a.box, "status": a.status.value,
                    "nodes": sorted(a.parts), "realizedOn": [],
                }
            al = info["allocation"]
            al["realizedOn"] = sorted(
                set(al["realizedOn"]) | set(a.realized_on)
            )
            trace_id = trace_id or a.trace_id
            alloc_ref = f"alloc/{a.alloc_id}"
            # audit trail union across holder CRs: each holder of a
            # multi-host allocation runs the same transition sequence
            # but stamps its OWN timestamps, so the dedup key is the
            # trail position + content, never the clock
            for i, t in enumerate(a.transitions):
                key = (i, t.get("status"), t.get("message"))
                if key in seen_transitions:
                    continue
                seen_transitions.add(key)
                timeline.append({
                    "ts": float(t.get("ts", 0.0)), "source": "audit",
                    "reason": TRANSITION_REASONS.get(
                        t.get("status", ""), t.get("status", "")
                    ),
                    "message": t.get("message", ""),
                })
    info["traceId"] = trace_id

    try:
        kube_events = client.list("Event", namespace=namespace)
    except ApiError:
        kube_events = []
    for ev in kube_events:
        io = ev.get("involvedObject") or {}
        if io.get("kind", "Pod") != "Pod":
            continue  # a Deployment/Service sharing the name is not us
        if io.get("name") != name:
            continue
        if io.get("namespace", namespace) != namespace:
            continue
        timeline.append({
            "ts": parse_timestamp(
                ev.get("lastTimestamp") or ev.get("firstTimestamp")
            ),
            "source": "event",
            "reason": ev.get("reason", ""),
            "message": ev.get("message", ""),
        })

    want_refs = {f"Pod/{namespace}/{name}"}
    if alloc_ref:
        want_refs.add(alloc_ref)
    for rec in _read_jsonl(events_path):
        if rec.get("objectRef") in want_refs or (
            trace_id and rec.get("traceId") == trace_id
        ):
            comp = rec.get("component", "")
            msg = rec.get("message", "")
            timeline.append({
                "ts": float(rec.get("ts", 0.0)), "source": "journal",
                "reason": rec.get("reason", ""),
                "message": f"[{comp}] {msg}".strip() if comp else msg,
                "_key": (rec.get("reason", ""), msg),
            })

    if trace_id:
        for rec in _read_jsonl(trace_path):
            if rec.get("traceId") != trace_id:
                continue
            msg = f"{rec.get('durationMs', 0):.3f}ms"
            if rec.get("error"):
                msg += f" error={rec['error']}"
            timeline.append({
                "ts": float(rec.get("start", 0.0)), "source": "span",
                "reason": rec.get("name", ""), "message": msg,
                # spans are never decision mirrors: repeats (decode
                # rounds, retried reconciles) are distinct entries
                "_key": ("span", rec.get("name", ""),
                         round(float(rec.get("start", 0.0)), 6)),
            })

    timeline.sort(key=lambda t: (t["ts"], t["source"]))
    # cross-source dedup: one DECISION lands on up to three surfaces
    # (journal + mirrored kube Event; transition journal + audit trail)
    # — and a multi-host allocation re-records it once per holder with
    # per-holder clocks. So the key is the decision's CONTENT (reason +
    # message), never a timestamp; the first source in (ts, source)
    # order wins. Journal-only events (kube transport, erased retry
    # epochs) have no twin and survive untouched.
    seen_keys: set = set()
    deduped = []
    for t in timeline:
        key = t.pop("_key", None) or (t["reason"], t["message"])
        if key in seen_keys:
            continue
        seen_keys.add(key)
        deduped.append(t)
    info["timeline"] = deduped
    return info


def render_locks(payload: dict) -> str:
    """Human rendering of a ``/v1/debug/locks`` body
    (utils/lockcheck.py): live per-thread held locks first — the
    hung-process question — then accumulated order cycles, long holds,
    and the hottest locks by total hold time."""
    lines = [f"lockcheck armed: {'yes' if payload.get('armed') else 'no'}"]
    if not payload.get("armed"):
        lines.append(
            "  (set TPUSLICE_LOCKCHECK=1 on the component to record "
            "held locks, ordering edges and hold times)"
        )
    live = payload.get("live", [])
    lines.append(f"Held now ({len(live)} thread(s)):")
    for t in live:
        held = " -> ".join(
            f"{h['name']}({h['heldSeconds']:.3f}s"
            + (f",depth={h['depth']}" if h.get("depth", 1) > 1 else "")
            + ")"
            for h in t["held"]
        )
        lines.append(f"  {t['thread']}: {held}")
    cycles = payload.get("cycles", [])
    if cycles:
        lines.append(f"Lock-order cycles ({len(cycles)}) — ABBA "
                     "deadlocks waiting for the right interleaving:")
        for c in cycles:
            lines.append(f"  {' -> '.join(c['chain'])}  "
                         f"threads={','.join(c.get('threads', []))}")
    long_holds = payload.get("longHolds", [])
    if long_holds:
        lines.append(f"Long holds ({len(long_holds)}):")
        for h in long_holds[-20:]:
            lines.append(f"  {h['name']}  {h['seconds']}s  "
                         f"thread={h['thread']}")
    holds = payload.get("holds", {})
    if holds:
        top = sorted(holds.items(), key=lambda kv: -kv[1]["totalSeconds"])
        lines.append("Hottest locks (by total hold time):")
        for name, st in top[:10]:
            lines.append(
                f"  {name}  count={st['count']}  "
                f"total={st['totalSeconds']}s  max={st['maxSeconds']}s"
            )
    edges = payload.get("edges", [])
    lines.append(f"Ordering edges recorded: {len(edges)}")
    return "\n".join(lines)


def render_describe(info: dict) -> str:
    """Human rendering of :func:`describe_pod` — the "why is my pod
    still gated?" answer (README walkthrough)."""
    lines = [
        f"Pod {info['namespace']}/{info['pod']}  "
        f"phase={info['phase'] or '?'}  "
        f"gated={'yes (' + ','.join(info['gates']) + ')' if info['gated'] else 'no'}"
    ]
    if info["error"]:
        lines.append(f"  error annotation: {info['error']}")
    if info["unhealthy"]:
        lines.append(f"  degraded: {info['unhealthy']}")
    al = info["allocation"]
    if al is not None:
        lines.append(
            f"Allocation {al['id']}  profile={al['profile']}  "
            f"box={al['box']}  status={al['status']}  "
            f"realized={len(al['realizedOn'])}/{len(al['nodes'])} "
            f"nodes={','.join(al['nodes'])}"
        )
    elif info["gated"] and not info["error"]:
        lines.append(
            "No allocation yet — the pod is waiting for the controller "
            "(look for NoCapacity/Rejected entries below)"
        )
    if info["traceId"]:
        lines.append(f"Trace {info['traceId']}  "
                     "(tpuslice trace-summary --trace <id> drills in)")
    lines.append(f"Timeline ({len(info['timeline'])} entries):")
    for t in info["timeline"]:
        when = "?" * 13  # matches the HH:MM:SS.mmmZ column width
        if t["ts"]:
            when = (
                datetime.datetime.fromtimestamp(
                    t["ts"], datetime.timezone.utc
                ).strftime("%H:%M:%S.%f")[:-3] + "Z"
            )
        # migration epochs render distinctly: every repacker decision
        # (Repack* reasons) and every transition the repacker stamped
        # "(repack" into gets the ⟳ marker, so a drain→teardown→re-grant
        # chain is visually separable from the original grant's chain
        mark = " "
        if t["reason"].startswith("Repack") or "(repack" in t["message"]:
            mark = "⟳"
        lines.append(
            f"{mark} {when:>13}  {t['source']:<7}  {t['reason']:<20}  "
            f"{t['message']}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tpuslice", description="instaslice_tpu operator CLI"
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    cat = sub.add_parser("catalog", help="print the profile catalog")
    cat.add_argument("generation", help="TPU generation, e.g. v5e")
    cat.add_argument("--max-chips", type=int, default=None)

    place = sub.add_parser("plan", help="simulate placing profiles on a mesh")
    place.add_argument("generation")
    place.add_argument("profiles", nargs="+", help="e.g. v5e-2x2 v5e-1x1")
    place.add_argument("--hosts", type=int, default=1)
    place.add_argument("--policy", default="best-fit")

    tr = sub.add_parser(
        "trace-summary",
        help="summarize spans from a TPUSLICE_TRACE_FILE JSONL or a "
        "live server's GET /v1/debug/trace (per-span p50/p95/max, "
        "slowest traces, single-trace drill-down)",
    )
    tr.add_argument("file", nargs="?", default="",
                    help="trace JSONL path (or use --url)")
    tr.add_argument("--url", default="",
                    help="live tpuslice-serve base url (e.g. "
                         "http://127.0.0.1:8000): read the in-memory "
                         "ring over GET /v1/debug/trace instead of a "
                         "file")
    tr.add_argument("--trace", default="", metavar="TRACE_ID",
                    help="dump every span of ONE trace (start order) "
                         "— the id an X-Trace-Id response header or a "
                         "slowest-traces row points at")
    tr.add_argument("--slowest", type=int, default=0, metavar="N",
                    help="also print the N slowest trace roots "
                         "(name, traceId, durationMs)")

    ev = sub.add_parser(
        "events",
        help="flight-recorder events from a TPUSLICE_EVENT_FILE JSONL "
        "or a live component's GET /v1/debug/events (one JSON line per "
        "event; --follow tails)",
    )
    ev.add_argument("file", nargs="?", default="",
                    help="event JSONL path (or use --url)")
    ev.add_argument("--url", default="",
                    help="live base url — a tpuslice-serve server or a "
                         "controller/agent health-probe address")
    ev.add_argument("--reason", default="",
                    help="only this reason (docs/OBSERVABILITY.md "
                         "catalog)")
    ev.add_argument("--object", default="",
                    help="only this objectRef (e.g. Pod/default/demo)")
    ev.add_argument("--trace", default="", metavar="TRACE_ID",
                    help="only events linked to this trace")
    ev.add_argument("--component", default="",
                    help="only this emitting component")
    ev.add_argument("-n", type=int, default=0, dest="last", metavar="N",
                    help="only the last N matching events")
    ev.add_argument("--follow", action="store_true",
                    help="keep tailing the source (Ctrl-C to stop)")

    fl = sub.add_parser(
        "fleet",
        help="fleet telemetry snapshot from a tpuslice-telemetry "
        "aggregator's GET /v1/fleet (goodput, per-class SLO "
        "attainment, burn-rate state, chip-hours); --follow polls, "
        "--trace renders one stitched cross-process timeline",
    )
    fl.add_argument("--url", required=True,
                    help="aggregator base URL (tpuslice-telemetry)")
    fl.add_argument("--trace", default="",
                    help="print the stitched timeline for this trace "
                    "id instead of the rollup snapshot")
    fl.add_argument("--follow", action="store_true",
                    help="keep polling (Ctrl-C to stop)")
    fl.add_argument("--interval", type=float, default=2.0,
                    help="seconds between --follow polls")

    pr = sub.add_parser(
        "profile",
        help="continuous-profiler export from a live component's GET "
        "/v1/debug/profile: per-segment p50/p95 summary rows, or "
        "--out trace.json for a Perfetto-loadable Chrome trace-event "
        "timeline (rounds + engine events + tracer spans interleaved)",
    )
    pr.add_argument("--url", required=True,
                    help="live base url (tpuslice-serve replica, "
                         "router, probe port, or telemetry server)")
    pr.add_argument("--out", default="",
                    help="write Chrome trace-event JSON here (open in "
                         "Perfetto / chrome://tracing) instead of "
                         "printing the segment summary")
    pr.add_argument("-n", type=int, default=512, dest="last",
                    metavar="N",
                    help="how many recent rounds/events/spans to "
                         "export (default 512, bounded by the rings)")

    wf = sub.add_parser(
        "waterfall",
        help="one request's latency waterfall (queue → admission → "
        "prefill → decode/spec rounds → preempt/resume → finish), "
        "stitched from round records + journal + trace by rid or "
        "trace id (GET /v1/debug/profile?rid=...)",
    )
    wf.add_argument("rid",
                    help="engine request id (the integer in stream "
                         "payloads) or a trace id (X-Trace-Id header)")
    wf.add_argument("--url", required=True,
                    help="the serving replica's base url")
    wf.add_argument("--json", action="store_true", dest="as_json",
                    help="raw payload instead of the ASCII waterfall")

    de = sub.add_parser(
        "describe",
        help="one object's merged control-plane timeline: Kubernetes "
        "Events + CR audit trail + journal + trace spans — the 'why is "
        "my pod still gated?' answer",
    )
    de.add_argument("kind", choices=["pod", "locks"])
    de.add_argument("name", nargs="?", default="")
    de.add_argument("--url", default="",
                    help="component base URL for `describe locks` — "
                    "any /v1/debug surface (replica, router, or a "
                    "controller/agent probe port)")
    de.add_argument("--namespace", default="default")
    de.add_argument("--operator-namespace",
                    default="instaslice-tpu-system",
                    help="namespace holding the TpuSlice CRs")
    de.add_argument("--kubeconfig", default="")
    de.add_argument("--events-file", default="",
                    help="TPUSLICE_EVENT_FILE JSONL to merge in")
    de.add_argument("--trace-file", default="",
                    help="TPUSLICE_TRACE_FILE JSONL to merge in")
    de.add_argument("--json", action="store_true", dest="as_json")

    st = sub.add_parser(
        "status",
        help="cluster slice status: per-node chips, health, allocations "
        "(the `kubectl get` + `nvidia-smi` half of the reference's demo "
        "transcript, from the CRs)",
    )
    st.add_argument("--kubeconfig", default="")
    st.add_argument("--namespace", default="instaslice-tpu-system")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")

    sb = sub.add_parser(
        "serve-bench",
        help="decode tokens/sec of the serving engine on this host's "
        "accelerator (BASELINE secondary metric: divide by chip count)",
    )
    sb.add_argument("--d-model", type=int, default=512)
    sb.add_argument("--n-layers", type=int, default=4)
    sb.add_argument("--n-heads", type=int, default=8)
    sb.add_argument("--n-kv-heads", type=int, default=0,
                    help="grouped-query attention KV heads (0 = MHA)")
    sb.add_argument("--d-ff", type=int, default=2048)
    sb.add_argument("--vocab", type=int, default=32000)
    sb.add_argument("--batch", type=int, default=8)
    sb.add_argument("--max-len", type=int, default=256)
    sb.add_argument("--prefill-len", type=int, default=16)
    sb.add_argument("--steps", type=int, default=30)
    sb.add_argument("--window", type=int, default=0,
                    help="sliding-window attention (0 = full causal)")
    sb.add_argument("--quantize", action="store_true",
                    help="int8 weights + int8 KV cache")
    sb.add_argument("--spec", action="store_true",
                    help="speculative decoding (int8 self-draft, "
                         "lossless greedy): reports tokens/sec and "
                         "accepted tokens per verify round")

    args = p.parse_args(argv)

    if args.cmd == "serve-bench" and args.prefill_len > args.max_len:
        p.error(
            f"--prefill-len {args.prefill_len} must be <= --max-len "
            f"{args.max_len}"
        )
    if args.cmd == "serve-bench" and args.quantize and args.spec:
        p.error(
            "--quantize with --spec would make the draft IDENTICAL to "
            "the int8 target (guaranteed full acceptance, pure "
            "overhead); --spec already uses an int8 draft against the "
            "full-precision target — pick one"
        )

    if args.cmd == "serve-bench":
        from instaslice_tpu.utils.tpulock import (
            TpuBusyError,
            claim_or_force_cpu,
        )

        try:
            # one-claimant rule: this subcommand initializes the host's
            # accelerator backend, so it must hold the host-wide TPU
            # claim (or pin CPU in-process when env-forced to cpu)
            claim = claim_or_force_cpu()
        except TpuBusyError as e:
            print(json.dumps({"error": str(e)}))
            return 3
        try:
            return _serve_bench(args)
        finally:
            if claim is not None:
                claim.release()



    if args.cmd == "status":
        from instaslice_tpu import KIND
        from instaslice_tpu.api.types import TpuSlice
        from instaslice_tpu.kube.real import build_client

        client = build_client(args.kubeconfig)
        nodes = []
        # multi-host allocations are fanned out to every participating
        # node's CR (controller/reconciler._write_allocation): merge by
        # allocation id so one slice is reported ONCE, with the union of
        # realized parts (the controller's own merged-view semantics)
        slices: dict = {}
        for m in sorted(
            client.list(KIND, namespace=args.namespace),
            key=lambda m: m["metadata"]["name"],
        ):
            ts = TpuSlice.from_manifest(m)
            nodes.append({
                "node": ts.name,
                "generation": ts.spec.generation,
                "chips": len(ts.spec.chips),
                "unhealthyChips": sorted(ts.status.unhealthy_chips),
                "prepared": len(ts.spec.prepared),
            })
            for aid, a in sorted(ts.spec.allocations.items()):
                s = slices.setdefault(aid, {
                    "id": aid,
                    "profile": a.profile,
                    "box": a.box,
                    "status": a.status.value,
                    "pods": sorted(p.pod_name for p in a.pods),
                    "nodes": sorted(a.parts),
                    "parts": len(a.parts),
                    "realizedOn": set(),
                })
                s["realizedOn"].update(a.realized_on)
        for s in slices.values():
            s["realizedOn"] = sorted(s["realizedOn"])
        out = {"nodes": nodes, "slices": sorted(
            slices.values(), key=lambda s: s["id"]
        )}
        if args.as_json:
            print(json.dumps(out))
            return 0
        if not nodes:
            print(f"no {KIND} objects in namespace {args.namespace}")
            return 0
        for n in nodes:
            bad = (f" unhealthy={n['unhealthyChips']}"
                   if n["unhealthyChips"] else "")
            print(f"{n['node']}: {n['generation']} chips={n['chips']}"
                  f" prepared={n['prepared']}{bad}")
        if out["slices"]:
            print("slices:")
        for s in out["slices"]:
            print(f"  {s['id'][:20]:<20} {s['profile']:<10} "
                  f"{s['box']:<14} {s['status']:<9} "
                  f"pods={','.join(s['pods'])} "
                  f"nodes={','.join(s['nodes'])} "
                  f"realized={len(s['realizedOn'])}/{s['parts']}")
        return 0

    if args.cmd == "trace-summary":
        return _trace_summary(p, args)

    if args.cmd == "events":
        try:
            return _events_cmd(p, args)
        except KeyboardInterrupt:
            return 0  # --follow's advertised stop path, not a crash

    if args.cmd == "fleet":
        try:
            return _fleet_cmd(args)
        except KeyboardInterrupt:
            return 0  # --follow's advertised stop path, not a crash

    if args.cmd == "profile":
        return _profile_cmd(args)

    if args.cmd == "waterfall":
        return _waterfall_cmd(args)

    if args.cmd == "describe" and args.kind == "locks":
        import urllib.request

        if not args.url:
            print(json.dumps(
                {"error": "describe locks needs --url <component>"}
            ))
            return 2
        try:
            with urllib.request.urlopen(
                args.url.rstrip("/") + "/v1/debug/locks", timeout=10
            ) as r:
                payload = json.loads(r.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI: message, not trace
            print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
            return 1
        if args.as_json:
            print(json.dumps(payload))
        else:
            print(render_locks(payload))
        return 0

    if args.cmd == "describe":
        from instaslice_tpu.kube.real import build_client

        if not args.name:
            print(json.dumps({"error": "describe pod needs a name"}))
            return 2
        client = build_client(args.kubeconfig)
        info = describe_pod(
            client, args.name, namespace=args.namespace,
            operator_namespace=args.operator_namespace,
            events_path=args.events_file, trace_path=args.trace_file,
        )
        if args.as_json:
            print(json.dumps(info))
        else:
            print(render_describe(info))
        return 0

    if args.cmd == "catalog":
        from instaslice_tpu.topology import profile_catalog

        for prof in profile_catalog(args.generation, args.max_chips):
            print(json.dumps({"name": prof.name, **prof.attributes()}))
        return 0

    if args.cmd == "plan":
        from instaslice_tpu.topology import (
            NodeGrid,
            Occupancy,
            TorusGroup,
            get_policy,
            parse_profile_name,
        )
        from instaslice_tpu.topology.grid import get_generation

        gen = get_generation(args.generation)
        hb = gen.host_bounds
        hosts = {
            f"host-{i}": NodeGrid(gen, host_offset=(i * hb[0], 0, 0))
            for i in range(args.hosts)
        }
        group = TorusGroup(
            "plan", gen, (hb[0] * args.hosts, hb[1], hb[2]), hosts
        )
        occ = Occupancy(group)
        pol = get_policy(args.policy)
        ok = True
        for i, name in enumerate(args.profiles):
            pl = pol.choose(group, parse_profile_name(name), occ)
            if pl is None:
                print(json.dumps({"request": name, "placed": False}))
                ok = False
                continue
            occ.occupy(pl.box, owner=str(i))
            print(
                json.dumps(
                    {
                        "request": name,
                        "placed": True,
                        "box": pl.box.key(),
                        "hosts": {
                            pt.node_name: pt.local_chip_ids(hb)
                            for pt in pl.parts
                        },
                    }
                )
            )
        return 0 if ok else 1

    return 2


if __name__ == "__main__":
    sys.exit(main())
