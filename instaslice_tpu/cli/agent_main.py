"""Per-node agent entry point (reference: ``cmd/daemonset/main.go:55-168``)."""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslice-agent",
        description="instaslice_tpu node agent: discovers TPU chips, "
        "realizes allocations, injects slice env.",
    )
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""),
                   help="this node's name (downward API NODE_NAME)")
    p.add_argument("--namespace", default="instaslice-tpu-system")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "fake", "native", "cloudtpu"],
                   help="device backend (see instaslice_tpu.device.select)")
    p.add_argument("--metrics-bind-address", default=":8084")
    p.add_argument("--health-probe-bind-address", default=":8085")
    p.add_argument("--kubeconfig", default="")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.node_name:
        print("error: --node-name or NODE_NAME env required", file=sys.stderr)
        return 2
    from instaslice_tpu.cli.runtime import run_agent

    return run_agent(args)


if __name__ == "__main__":
    sys.exit(main())
