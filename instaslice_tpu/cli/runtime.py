"""Runtime wiring for the CLI entry points.

Builds the kube client, device backend, and reconcilers, then runs the
watch loops. Populated as layers land; each runner degrades with a clear
error instead of a traceback when its layer is unavailable.
"""

from __future__ import annotations

import sys


def run_controller(args) -> int:
    try:
        from instaslice_tpu.controller.runner import ControllerRunner
    except ImportError as e:
        print(f"controller unavailable: {e}", file=sys.stderr)
        return 1
    return ControllerRunner.from_args(args).run()


def run_agent(args) -> int:
    try:
        from instaslice_tpu.agent.runner import AgentRunner
    except ImportError as e:
        print(f"agent unavailable: {e}", file=sys.stderr)
        return 1
    return AgentRunner.from_args(args).run()


def run_deviceplugin(args) -> int:
    try:
        from instaslice_tpu.deviceplugin.server import serve
    except ImportError as e:
        print(f"device plugin unavailable: {e}", file=sys.stderr)
        return 1
    return serve(args)
