"""Process entry points (reference analog: ``cmd/controller/main.go``,
``cmd/daemonset/main.go``)."""
