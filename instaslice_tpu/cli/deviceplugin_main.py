"""Kubelet device-plugin entry point advertising ``google.com/tpu``.

The reference outsources this to the NVIDIA GPU operator and kicks it via a
node-label toggle (``instaslice_daemonset.go:474-497``); here it is a real
in-tree component (SURVEY.md §2a row 3).
"""

from __future__ import annotations

import argparse
import sys

from instaslice_tpu.api.constants import TPU_RESOURCE


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpuslice-deviceplugin",
        description=f"kubelet device plugin advertising {TPU_RESOURCE}",
    )
    p.add_argument("--plugin-dir", default="/var/lib/kubelet/device-plugins")
    p.add_argument("--backend", default="auto")
    p.add_argument("--resource", default=TPU_RESOURCE)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from instaslice_tpu.cli.runtime import run_deviceplugin

    return run_deviceplugin(args)


if __name__ == "__main__":
    sys.exit(main())
