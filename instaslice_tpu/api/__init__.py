"""``TpuSlice`` CR data model — reference analog: ``api/v1alpha1/``.

The reference defines a per-node ``Instaslice`` CR holding GPU inventory,
a MIG profile/placement catalog, desired allocations, and realized slices
(``/root/reference/api/v1alpha1/instaslice_types.go:23-102``). This package
defines the TPU equivalent with two reference weaknesses fixed (SURVEY.md
§7 quirks): statuses are typed enums with a validated transition graph, and
the operator namespace is configurable instead of hardcoded ``"default"``.
"""

from instaslice_tpu.api.types import (
    AllocationDetails,
    AllocationStatus,
    PodRef,
    PreparedDetails,
    PreparedPart,
    TpuSlice,
    TpuSliceSpec,
    TpuSliceStatus,
    slice_uuid_for,
)
from instaslice_tpu.api.crd import crd_manifest
