"""CustomResourceDefinition manifest for ``TpuSlice``.

Reference analog: the controller-gen output
``/root/reference/config/crd/bases/inference.codeflare.dev_instaslices.yaml``
(schema for Spec.{MigGPUUUID, Allocations, Prepared, Migplacement},
Status.Processed). Generated in code here so the schema can never drift
from :mod:`instaslice_tpu.api.types`.
"""

from __future__ import annotations

from instaslice_tpu.api.constants import GROUP, KIND, PLURAL, VERSION

_ALLOCATION_PROPS = {
    "allocId": {"type": "string"},
    "pods": {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {
                "podUUID": {"type": "string"},
                "podName": {"type": "string"},
                "namespace": {"type": "string"},
                "workerId": {"type": "integer"},
                "handoffName": {"type": "string"},
            },
            "required": ["podUUID", "podName"],
        },
    },
    "profile": {"type": "string"},
    "torusGroup": {"type": "string"},
    "box": {"type": "string"},
    "parts": {
        "type": "object",
        "additionalProperties": {
            "type": "object",
            "properties": {
                "workerId": {"type": "integer"},
                "localBox": {"type": "string"},
            },
            "required": ["workerId", "localBox"],
        },
    },
    "status": {
        "type": "string",
        "enum": ["creating", "created", "ungated", "deleted", "failed"],
    },
    "realizedOn": {"type": "array", "items": {"type": "string"}},
    "message": {"type": "string"},
    "createdAt": {"type": "number"},
    "deletionRequestedAt": {"type": "number"},
    # observability: the grant's trace id (minted at pod admission);
    # without this property a structural-schema API server would PRUNE
    # the field on write and silently break end-to-end trace
    # propagation (docs/OBSERVABILITY.md)
    "traceId": {"type": "string"},
    # flight recorder: the persisted audit trail — last N status
    # transitions with timestamps + messages (same pruning hazard as
    # traceId; docs/OBSERVABILITY.md "Events & audit trail")
    "transitions": {
        "type": "array",
        "items": {
            "type": "object",
            "properties": {
                "status": {"type": "string"},
                "ts": {"type": "number"},
                "message": {"type": "string"},
            },
            "required": ["status", "ts"],
        },
    },
    # crash consistency: the placement-attempt epoch (docs/RECOVERY.md)
    # — a restarted controller re-places with epoch+1 so half-landed
    # copies from a crashed writer are distinguishable; pruning it
    # would silently merge stale epochs back into the cluster truth
    "attemptEpoch": {"type": "integer"},
}

_PREPARED_PART_PROPS = {
    "nodeName": {"type": "string"},
    "workerId": {"type": "integer"},
    "localBox": {"type": "string"},
    "chipIds": {"type": "array", "items": {"type": "integer"}},
    "deviceHandle": {"type": "string"},
}

_SPEC_SCHEMA = {
    "type": "object",
    "properties": {
        "generation": {"type": "string"},
        "hostOffset": {
            "type": "array",
            "items": {"type": "integer"},
            "minItems": 3,
            "maxItems": 3,
        },
        "torusGroup": {"type": "string"},
        "chips": {"type": "object", "additionalProperties": {"type": "string"}},
        "profiles": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "name": {"type": "string"},
                    "chips": {"type": "integer"},
                    "x": {"type": "integer"},
                    "y": {"type": "integer"},
                    "z": {"type": "integer"},
                    "hosts": {"type": "integer"},
                    "hbmGiB": {"type": "integer"},
                },
                "required": ["name"],
            },
        },
        "allocations": {
            "type": "object",
            "additionalProperties": {
                "type": "object",
                "properties": _ALLOCATION_PROPS,
                "required": ["allocId", "pods", "profile", "box", "status"],
            },
        },
        "prepared": {
            "type": "object",
            "additionalProperties": {
                "type": "object",
                "properties": {
                    "sliceUUID": {"type": "string"},
                    "podUUID": {"type": "string"},
                    "profile": {"type": "string"},
                    "box": {"type": "string"},
                    "parts": {
                        "type": "object",
                        "additionalProperties": {
                            "type": "object",
                            "properties": _PREPARED_PART_PROPS,
                        },
                    },
                },
                "required": ["sliceUUID", "profile", "box"],
            },
        },
    },
}

_STATUS_SCHEMA = {
    "type": "object",
    "properties": {
        "processed": {"type": "boolean"},
        "conditions": {
            "type": "array",
            "items": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
        "unhealthyChips": {"type": "array", "items": {"type": "integer"}},
    },
}


def crd_manifest() -> dict:
    """The full CRD object, ready to apply/serve."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": f"{KIND}List",
                "plural": PLURAL,
                "singular": KIND.lower(),
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": _SPEC_SCHEMA,
                                "status": _STATUS_SCHEMA,
                            },
                        }
                    },
                    "additionalPrinterColumns": [
                        {
                            "name": "Generation",
                            "type": "string",
                            "jsonPath": ".spec.generation",
                        },
                        {
                            "name": "Group",
                            "type": "string",
                            "jsonPath": ".spec.torusGroup",
                        },
                        {
                            "name": "Processed",
                            "type": "boolean",
                            "jsonPath": ".status.processed",
                        },
                    ],
                }
            ],
        },
    }
