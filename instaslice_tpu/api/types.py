"""Typed data model for the ``TpuSlice`` custom resource.

Field-by-field mapping to the reference
(``/root/reference/api/v1alpha1/instaslice_types.go``):

================================  ======================================
reference (Instaslice)            this framework (TpuSlice)
================================  ======================================
``Spec.MigGPUUUID`` (:66)         ``spec.chips`` — chip id → device path
``Spec.Migplacement`` (:71)       ``spec.profiles`` — profile catalog
``Spec.Allocations`` (:68)        ``spec.allocations`` — desired slices
``Spec.Prepared`` (:70)           ``spec.prepared`` — realized slices
``Status.Processed`` (:97)        ``status.processed``
(absent)                          ``spec.generation/hostOffset/torusGroup``
                                  — multi-host placement inputs
================================  ======================================

Objects serialize to/from plain camelCase dicts shaped like K8s manifests;
the kube layer moves dicts, reconcilers work with these types.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, List, Optional, Tuple

from instaslice_tpu.api.constants import (
    API_VERSION,
    KIND,
    TRANSITION_REASONS,
)
from instaslice_tpu.topology.grid import Coord, NodeGrid, Shape, get_generation
from instaslice_tpu.topology.placement import Box, HostPart, Placement
from instaslice_tpu.topology.profiles import TopologyProfile, parse_profile_name


class AllocationStatus(str, enum.Enum):
    """Allocation lifecycle — typed, unlike the reference's bare strings
    (``instaslice_controller.go:164-182`` flips ``"creating"/"created"/
    "ungated"/"deleted"`` literals inline).

    ``FAILED`` is new: the reference logs device errors and carries on
    (``instaslice_daemonset.go:172-189``, flagged in SURVEY.md §5); here a
    failed realization is a first-class state the controller can retry or
    surface.
    """

    CREATING = "creating"   # controller chose a placement, agent(s) must realize
    CREATED = "created"     # all host parts realized on hardware
    UNGATED = "ungated"     # scheduling gate removed, pod may bind
    DELETED = "deleted"     # teardown requested; agents must release chips
    FAILED = "failed"       # realization failed; controller decides retry


# Legal transitions (from → {to}). Anything else is a programming error.
_TRANSITIONS = {
    AllocationStatus.CREATING: {
        AllocationStatus.CREATED,
        AllocationStatus.FAILED,
        AllocationStatus.DELETED,
    },
    AllocationStatus.CREATED: {
        AllocationStatus.UNGATED,
        AllocationStatus.DELETED,
        AllocationStatus.FAILED,
    },
    AllocationStatus.UNGATED: {AllocationStatus.DELETED},
    AllocationStatus.FAILED: {
        AllocationStatus.CREATING,
        AllocationStatus.DELETED,
    },
    AllocationStatus.DELETED: set(),
}


#: Audit-trail bound: the CR keeps the last N status transitions (a full
#: grant lifecycle is ~5; retries add a few more). Bounded so a
#: crash-looping allocation cannot grow its CR without limit.
AUDIT_TRAIL_MAX = 10


def check_transition(old: AllocationStatus, new: AllocationStatus) -> None:
    if new == old:
        return
    if new not in _TRANSITIONS[old]:
        raise ValueError(f"illegal allocation transition {old.value} -> {new.value}")


@dataclasses.dataclass
class PodRef:
    """One consumer pod of an allocation. Single-host slices have exactly
    one; multi-host slices have one pod per host, each bound to a worker id
    (and through it to the host serving that worker)."""

    pod_uuid: str
    pod_name: str
    namespace: str
    worker_id: int = 0
    # Stable name for the handoff ConfigMap + per-pod extended resource
    # when the pod is template-managed (Deployment pods get generated
    # names, so a fixed ``envFrom`` / resource limit in the template can't
    # reference the real pod name). "" = use pod_name.
    handoff_name: str = ""

    @property
    def handoff(self) -> str:
        return self.handoff_name or self.pod_name

    def to_dict(self) -> dict:
        d = {
            "podUUID": self.pod_uuid,
            "podName": self.pod_name,
            "namespace": self.namespace,
            "workerId": self.worker_id,
        }
        if self.handoff_name:
            d["handoffName"] = self.handoff_name
        return d

    @staticmethod
    def from_dict(d: dict) -> "PodRef":
        return PodRef(
            pod_uuid=d["podUUID"],
            pod_name=d["podName"],
            namespace=d.get("namespace", ""),
            worker_id=int(d.get("workerId", 0)),
            handoff_name=d.get("handoffName", ""),
        )


@dataclasses.dataclass
class AllocationDetails:
    """Desired slice for one pod or pod group (reference:
    ``AllocationDetails``, instaslice_types.go:74-87 — pod identity, GPU
    UUID, start/size, status). The TPU version stores the global box plus
    the per-host decomposition so one allocation can fan out to several
    node agents, and a pod list so multi-host slices (one pod per host)
    are a single allocation — new capability, SURVEY.md §7."""

    alloc_id: str                    # pod UUID for singletons, group id else
    pods: List[PodRef]
    profile: str                     # canonical profile name, e.g. v5e-2x2
    torus_group: str
    box: str                         # Box.key() in global mesh coords
    # node name → (worker_id, local Box.key())
    parts: Dict[str, Tuple[int, str]]
    status: AllocationStatus = AllocationStatus.CREATING
    # nodes that have realized their part (subset of parts.keys())
    realized_on: List[str] = dataclasses.field(default_factory=list)
    message: str = ""                # last error for FAILED
    created_at: float = 0.0          # unix secs; grant-latency metric input
    deletion_requested_at: float = 0.0
    # observability: the trace id minted when the controller admitted the
    # gated pod — every span the controller, agents, and device layer
    # emit for this allocation carries it, so one grant is queryable
    # end-to-end (utils/trace.py; docs/OBSERVABILITY.md)
    trace_id: str = ""
    # audit trail: the last AUDIT_TRAIL_MAX status transitions, each
    # {"status", "ts", "message"} — persisted through to_dict/from_dict
    # so "why did this allocation end up here" survives controller
    # restarts (recorded by set_status, the transition choke point)
    transitions: List[dict] = dataclasses.field(default_factory=list)
    # crash consistency (docs/RECOVERY.md): which placement attempt this
    # record belongs to. A controller that dies mid-fan-out can leave an
    # old epoch's copy on one CR while its successor re-places the same
    # alloc_id at a new box — the merged view must never union
    # realized_on/status across epochs (a crashed writer's half-landed
    # state is NOT a concurrent writer). 0 = pre-epoch record (legacy
    # CRs), merged like epoch 0.
    attempt_epoch: int = 0

    def to_dict(self) -> dict:
        return {
            "allocId": self.alloc_id,
            "pods": [p.to_dict() for p in self.pods],
            "profile": self.profile,
            "torusGroup": self.torus_group,
            "box": self.box,
            "parts": {
                n: {"workerId": wid, "localBox": lb}
                for n, (wid, lb) in sorted(self.parts.items())
            },
            "status": self.status.value,
            "realizedOn": sorted(self.realized_on),
            "message": self.message,
            "createdAt": self.created_at,
            "deletionRequestedAt": self.deletion_requested_at,
            **({"traceId": self.trace_id} if self.trace_id else {}),
            **({"transitions": [dict(t) for t in self.transitions]}
               if self.transitions else {}),
            **({"attemptEpoch": self.attempt_epoch}
               if self.attempt_epoch else {}),
        }

    @staticmethod
    def from_dict(d: dict) -> "AllocationDetails":
        return AllocationDetails(
            alloc_id=d["allocId"],
            pods=[PodRef.from_dict(p) for p in d.get("pods", [])],
            profile=d["profile"],
            torus_group=d.get("torusGroup", ""),
            box=d["box"],
            parts={
                n: (p["workerId"], p["localBox"])
                for n, p in d.get("parts", {}).items()
            },
            status=AllocationStatus(d.get("status", "creating")),
            realized_on=list(d.get("realizedOn", [])),
            message=d.get("message", ""),
            created_at=float(d.get("createdAt", 0.0)),
            deletion_requested_at=float(d.get("deletionRequestedAt", 0.0)),
            trace_id=d.get("traceId", ""),
            transitions=[dict(t) for t in d.get("transitions", [])],
            attempt_epoch=int(d.get("attemptEpoch", 0)),
        )

    def global_box(self) -> Box:
        return Box.from_key(self.box)

    def set_status(self, new: AllocationStatus, message: str = "") -> None:
        """THE allocation state-transition choke point: validates the
        edge, then records it on the persisted audit trail and in the
        process flight recorder (obs/journal.py) with the grant's
        trace id — one call, three observability surfaces."""
        check_transition(self.status, new)
        old = self.status
        self.status = new
        if message:
            self.message = message
        if new != old:
            self._record_transition(new, message)

    def _record_transition(self, status: AllocationStatus,
                           message: str) -> None:
        from instaslice_tpu.obs.journal import get_journal

        extra = (
            {"attempt_epoch": self.attempt_epoch}
            if self.attempt_epoch else {}
        )
        try:
            # chip count rides every transition so the telemetry plane
            # can integrate chip-seconds (ungated→deleted × chips) from
            # the journal alone, without re-resolving profiles
            extra["chips"] = len(self.global_box().coords())
        except (ValueError, KeyError, IndexError):
            pass  # malformed box key: the event still records
        ev = get_journal().emit(
            "allocation",
            reason=TRANSITION_REASONS[status.value],
            object_ref=f"alloc/{self.alloc_id}",
            message=message,
            trace_id=self.trace_id,
            status=status.value,
            **extra,
        )
        # the trail entry shares the journal event's timestamp, so the
        # describe-pod timeline dedupes the two surfaces exactly
        self.transitions.append({
            "status": status.value,
            "ts": round(ev.ts, 6),
            "message": message,
        })
        del self.transitions[:-AUDIT_TRAIL_MAX]

    def node_for_worker(self, worker_id: int) -> Optional[str]:
        for n, (wid, _) in self.parts.items():
            if wid == worker_id:
                return n
        return None

    def pods_on_node(self, node_name: str) -> List[PodRef]:
        part = self.parts.get(node_name)
        if part is None:
            return []
        wid = part[0]
        return [p for p in self.pods if p.worker_id == wid]

    def local_chip_ids(self, node_name: str, host_bounds: Shape) -> List[int]:
        """Local chip ids this allocation occupies on ``node_name`` (empty
        when the node serves no part). Shared by the agent (reservation,
        health intersection) and the controller (degraded-slice detection)."""
        part = self.parts.get(node_name)
        if part is None:
            return []
        from instaslice_tpu.topology.grid import coord_to_id

        return sorted(
            coord_to_id(c, host_bounds)
            for c in Box.from_key(part[1]).coords()
        )

    def fully_realized(self) -> bool:
        return set(self.realized_on) >= set(self.parts)

    @staticmethod
    def from_placement(
        placement: Placement,
        pods: List[PodRef],
        alloc_id: str = "",
        now: Optional[float] = None,
        trace_id: str = "",
        note: str = "",
        attempt_epoch: int = 0,
    ) -> "AllocationDetails":
        """``note`` is appended to the seed transition's message — the
        repacker stamps its re-grants with it so a migration epoch is
        distinguishable from an original grant in the audit trail and
        the ``describe pod`` timeline. ``attempt_epoch`` stamps the
        placement attempt (crash recovery re-places with the prior
        epoch + 1 so stale half-landed copies are distinguishable)."""
        if not pods:
            raise ValueError("allocation needs at least one pod")
        alloc = AllocationDetails(
            alloc_id=alloc_id or pods[0].pod_uuid,
            pods=list(pods),
            profile=placement.profile.name,
            torus_group=placement.group_id,
            box=placement.box.key(),
            parts={
                p.node_name: (p.worker_id, p.local_box.key())
                for p in placement.parts
            },
            status=AllocationStatus.CREATING,
            created_at=time.time() if now is None else now,
            trace_id=trace_id,
            attempt_epoch=max(0, int(attempt_epoch)),
        )
        # seed the audit trail: a freshly placed allocation IS the
        # creating transition (set_status only sees later edges)
        alloc._record_transition(
            AllocationStatus.CREATING,
            f"{placement.profile.name} at {placement.box.key()}"
            + (f" ({note})" if note else ""),
        )
        return alloc


@dataclasses.dataclass
class PreparedPart:
    """One node's realized share of a slice (reference:
    ``PreparedDetails`` carries parent/gi/ci ids per MIG UUID,
    instaslice_types.go:89-95; ours carries local chip ids + the device
    handle returned by the device layer)."""

    node_name: str
    worker_id: int
    local_box: str                  # Box.key() in host-local coords
    chip_ids: List[int]             # local chip ids (TPU_VISIBLE_CHIPS)
    device_handle: str = ""         # backend-specific reservation handle

    def to_dict(self) -> dict:
        return {
            "nodeName": self.node_name,
            "workerId": self.worker_id,
            "localBox": self.local_box,
            "chipIds": list(self.chip_ids),
            "deviceHandle": self.device_handle,
        }

    @staticmethod
    def from_dict(d: dict) -> "PreparedPart":
        return PreparedPart(
            node_name=d["nodeName"],
            worker_id=d["workerId"],
            local_box=d["localBox"],
            chip_ids=list(d["chipIds"]),
            device_handle=d.get("deviceHandle", ""),
        )


def slice_uuid_for(alloc_id: str, multihost: bool = False) -> str:
    """Deterministic per-allocation slice uuid — every agent serving a
    multi-host allocation derives the same id with no rendezvous, and the
    controller uses it to match ``prepared`` entries to allocations.

    Multi-host allocations get a distinguishable prefix: a node-local part
    of a multi-host slice is a full-host tile, which would otherwise be
    indistinguishable from a standalone whole-host reservation — and the
    device plugin must never advertise another job's part as an
    allocatable slice device."""
    return f"sl-mh-{alloc_id}" if multihost else f"sl-{alloc_id}"


def is_multihost_slice_uuid(suid: str) -> bool:
    return suid.startswith("sl-mh-")


@dataclasses.dataclass
class PreparedDetails:
    """A realized slice, keyed by slice UUID in ``spec.prepared``.

    ``pod_uuid == ""`` marks a dangling slice adopted at boot discovery —
    same convention as the reference (``discoverDanglingSlices`` records
    ``PodUUID: ""``, instaslice_daemonset.go:666-748, and the placement
    engine counts those as occupied, instaslice_controller.go:312-320).
    """

    slice_uuid: str
    pod_uuid: str
    profile: str
    box: str                        # global Box.key()
    parts: Dict[str, PreparedPart]  # node name → part

    def to_dict(self) -> dict:
        return {
            "sliceUUID": self.slice_uuid,
            "podUUID": self.pod_uuid,
            "profile": self.profile,
            "box": self.box,
            "parts": {n: p.to_dict() for n, p in sorted(self.parts.items())},
        }

    @staticmethod
    def from_dict(d: dict) -> "PreparedDetails":
        return PreparedDetails(
            slice_uuid=d["sliceUUID"],
            pod_uuid=d.get("podUUID", ""),
            profile=d["profile"],
            box=d["box"],
            parts={
                n: PreparedPart.from_dict(p)
                for n, p in d.get("parts", {}).items()
            },
        )


@dataclasses.dataclass
class TpuSliceSpec:
    """Per-node spec (reference: ``InstasliceSpec``,
    instaslice_types.go:64-72)."""

    generation: str = ""             # e.g. "v5e"
    host_offset: Coord = (0, 0, 0)   # this host's corner in its torus group
    torus_group: str = ""            # hosts sharing a physical mesh
    chips: Dict[str, str] = dataclasses.field(default_factory=dict)
    #   local chip id (str for k8s map keys) → device path ("/dev/accel0")
    profiles: List[dict] = dataclasses.field(default_factory=list)
    #   published catalog entries: {"name": ..., attrs...}
    allocations: Dict[str, AllocationDetails] = dataclasses.field(
        default_factory=dict
    )                                # pod UUID → desired
    prepared: Dict[str, PreparedDetails] = dataclasses.field(
        default_factory=dict
    )                                # slice UUID → realized

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "hostOffset": list(self.host_offset),
            "torusGroup": self.torus_group,
            "chips": dict(sorted(self.chips.items())),
            "profiles": list(self.profiles),
            "allocations": {
                k: v.to_dict() for k, v in sorted(self.allocations.items())
            },
            "prepared": {
                k: v.to_dict() for k, v in sorted(self.prepared.items())
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "TpuSliceSpec":
        off = d.get("hostOffset", [0, 0, 0])
        return TpuSliceSpec(
            generation=d.get("generation", ""),
            host_offset=(int(off[0]), int(off[1]), int(off[2])),
            torus_group=d.get("torusGroup", ""),
            chips=dict(d.get("chips", {})),
            profiles=list(d.get("profiles", [])),
            allocations={
                k: AllocationDetails.from_dict(v)
                for k, v in d.get("allocations", {}).items()
            },
            prepared={
                k: PreparedDetails.from_dict(v)
                for k, v in d.get("prepared", {}).items()
            },
        )

    def node_grid(self) -> NodeGrid:
        return NodeGrid(
            generation=get_generation(self.generation),
            host_offset=self.host_offset,
            torus_group=self.torus_group,
        )


@dataclasses.dataclass
class TpuSliceStatus:
    """Reference: ``InstasliceStatus.Processed`` (instaslice_types.go:97)
    — a string "true"; here a bool plus an observability surface.

    ``unhealthy_chips`` is the node agent's published per-chip health
    (local chip ids currently failed); the controller's placement engine
    treats them as occupied. No reference analog — SURVEY.md §5 flags "no
    health monitoring of slices" as a gap to close."""

    processed: bool = False
    conditions: List[dict] = dataclasses.field(default_factory=list)
    unhealthy_chips: List[int] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "processed": self.processed,
            "conditions": list(self.conditions),
            "unhealthyChips": sorted(self.unhealthy_chips),
        }

    @staticmethod
    def from_dict(d: dict) -> "TpuSliceStatus":
        return TpuSliceStatus(
            processed=bool(d.get("processed", False)),
            conditions=list(d.get("conditions", [])),
            unhealthy_chips=[int(c) for c in d.get("unhealthyChips", [])],
        )


@dataclasses.dataclass
class TpuSlice:
    """The full CR: one per node, named after the node (reference creates
    the CR named ``$NODE_NAME``, instaslice_daemonset.go:567-582)."""

    name: str
    namespace: str
    spec: TpuSliceSpec = dataclasses.field(default_factory=TpuSliceSpec)
    status: TpuSliceStatus = dataclasses.field(default_factory=TpuSliceStatus)
    resource_version: str = ""

    def to_manifest(self) -> dict:
        return {
            "apiVersion": API_VERSION,
            "kind": KIND,
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                **(
                    {"resourceVersion": self.resource_version}
                    if self.resource_version
                    else {}
                ),
            },
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @staticmethod
    def from_manifest(m: dict) -> "TpuSlice":
        md = m.get("metadata", {})
        return TpuSlice(
            name=md.get("name", ""),
            namespace=md.get("namespace", ""),
            spec=TpuSliceSpec.from_dict(m.get("spec", {})),
            status=TpuSliceStatus.from_dict(m.get("status", {})),
            resource_version=md.get("resourceVersion", ""),
        )

    def profile_objects(self) -> List[TopologyProfile]:
        return [parse_profile_name(p["name"]) for p in self.spec.profiles]
