"""Canonical home of every shared gate / finalizer / resource /
annotation name — the ONLY module allowed to spell them as string
literals.

``tools/slicelint.py``'s ``name-literal`` rule enforces that: any other
module writing ``"tpu.instaslice.dev/..."`` / ``"google.com/tpu..."`` /
``"org.instaslice/..."`` inline fails ``make lint``. A name that exists
in two places drifts in two places — the reference shipped its
scheduling gate with a typo (``org.instaslice/accelarator``,
``/root/reference/samples/test-pod.yaml``) and could never fix it
because the literal was replicated across the controller, daemonset,
webhook, and samples. Here the misspelling survives only as
:data:`LEGACY_GATE_NAME`, honored for interop, and the spelling is
corrected exactly once.

This module is import-time pure (no package ``__init__`` dependencies):
``instaslice_tpu/__init__.py`` re-exports from here, so everything below
must stay standalone literals/f-strings.
"""

GROUP = "tpu.instaslice.dev"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "TpuSlice"
PLURAL = "tpuslices"

# --------------------------------------------------------------- gating

#: Scheduling gate + finalizer (reference: ``org.instaslice/accelarator``
#: — typo deliberately not replicated; see LEGACY_GATE_NAME).
GATE_NAME = f"{GROUP}/accelerator"
FINALIZER = f"{GROUP}/accelerator"

#: The reference operator's gate, canonical misspelling included
#: (``accelarator``). Pods gated by a reference-era webhook/mutator carry
#: THIS name; the controller recognizes it on ungate so a migration
#: doesn't strand them Pending forever.
LEGACY_GATE_NAME = "org.instaslice/accelarator"

# ------------------------------------------------------------ resources

#: Per-pod extended resource prefix (reference: ``org.instaslice/<pod>``).
POD_RESOURCE_PREFIX = f"{GROUP}/"

#: Extended resource advertised by the whole-chip device plugin
#: (reference: ``nvidia.com/mig-*`` via the NVIDIA GPU operator).
TPU_RESOURCE = "google.com/tpu"

#: Per-profile slice resources (``google.com/tpu-v5e-2x2``) advertised by
#: the slice device-plugin manager and requested in pod limits.
TPU_PROFILE_RESOURCE_PREFIX = f"{TPU_RESOURCE}-"

# ---------------------------------------------------- pod annotations

PROFILE_ANNOTATION = f"{GROUP}/profile"
GROUP_ANNOTATION = f"{GROUP}/group"
GROUP_SIZE_ANNOTATION = f"{GROUP}/group-size"
HANDOFF_ANNOTATION = f"{GROUP}/handoff-name"
UNHEALTHY_ANNOTATION = f"{GROUP}/slice-unhealthy"
RESTART_ON_FAILURE_ANNOTATION = f"{GROUP}/restart-on-failure"
ERROR_ANNOTATION = f"{GROUP}/error"

#: Repacker opt-out: pods annotated ``"true"`` are never selected as
#: migration victims by the defragmentation loop (controller/defrag.py)
#: — a workload that cannot tolerate a drain→re-grant cycle pins its
#: chips for life.
REPACK_OPTOUT_ANNOTATION = f"{GROUP}/no-repack"

#: Device-plugin allocate-response annotations (surfaced on the pod by
#: the kubelet / the sim's kubelet emulator).
CHIPS_ANNOTATION = f"{GROUP}/chips"
SLICE_DEVICE_ANNOTATION = f"{GROUP}/slice-device"
DEVICE_PATHS_ANNOTATION = f"{GROUP}/device-paths"
KUBELET_ENV_CHIPS_ANNOTATION = f"{GROUP}/kubelet-env-chips"

#: The allocation's grant trace id, mirrored onto the Kubernetes Event
#: objects the flight recorder posts — `kubectl get events -o yaml` links
#: straight into the trace tooling (docs/OBSERVABILITY.md).
TRACE_ID_ANNOTATION = f"{GROUP}/trace-id"

#: Demand→supply causality stamp: a serving-side trace id recorded on a
#: pod whose admission was requested ON BEHALF of a blocked request (a
#: router/autoscaler reacting to ``NoCapacity``). The controller copies
#: it onto the grant's ``controller.allocate`` span and the ``Admitted``
#: journal event as a ``caused_by`` attribute, letting the telemetry
#: plane stitch the serving trace and the grant trace that unblocked it
#: into ONE causal timeline (docs/OBSERVABILITY.md "Fleet telemetry").
CAUSED_BY_ANNOTATION = f"{GROUP}/caused-by"

# --------------------------------------------------------------- events

#: Flight-recorder ``reason`` catalog (docs/OBSERVABILITY.md). Every
#: journal event and every mirrored Kubernetes ``Event`` names its reason
#: from HERE — slicelint's ``event-reason-literal`` rule fails any other
#: module passing a string literal as a ``reason=``, so the catalog (and
#: the dashboards / validators keyed on it) cannot drift.

# allocation lifecycle transitions (AllocationDetails.set_status)
REASON_SLICE_CREATING = "SliceCreating"
REASON_SLICE_CREATED = "SliceCreated"
REASON_SLICE_UNGATED = "SliceUngated"
REASON_SLICE_FAILED = "SliceFailed"
REASON_SLICE_DELETED = "SliceDeleted"

# controller decisions (pod-scoped; mirrored as Kubernetes Events)
REASON_ADMITTED = "Admitted"
REASON_PLACED = "Placed"
REASON_NO_CAPACITY = "NoCapacity"
REASON_REJECTED = "Rejected"
REASON_RETRYING = "Retrying"
REASON_UNGATED = "Ungated"
REASON_DEGRADED = "SliceDegraded"
REASON_HEALED = "SliceHealed"
REASON_HEALTH_EVICTED = "HealthEvicted"

# repacker (controller/defrag.py): live slice defragmentation. Each
# migration is one drain→teardown→re-grant epoch under its own trace id;
# Planned lands on the capacity-starved pod that triggered the plan,
# Migrating/Done/Failed land on the migrated pods.
REASON_REPACK_PLANNED = "RepackPlanned"
REASON_REPACK_MIGRATING = "RepackMigrating"
REASON_REPACK_DONE = "RepackDone"
REASON_REPACK_FAILED = "RepackFailed"

# node agent / device plane
REASON_REALIZED = "SliceRealized"
REASON_REALIZE_FAILED = "SliceRealizeFailed"
REASON_TORN_DOWN = "SliceTornDown"
REASON_CHIP_UNHEALTHY = "ChipUnhealthy"
REASON_CHIP_HEALED = "ChipHealed"

# kube transport
REASON_BREAKER_OPEN = "KubeBreakerOpen"
REASON_BACKOFF = "KubeBackoff"
REASON_WATCH_RECONNECT = "KubeWatchReconnect"

# serving data plane
REASON_DRAIN_BEGIN = "DrainBegin"
REASON_DRAIN_END = "DrainEnd"
REASON_SHED = "RequestShed"
REASON_DRAINED = "RequestDrained"

# serving scheduler (serving/scheduler.py): SLO-aware preemption. A
# best-effort request parked so a latency-class request makes its TTFT
# target; Resumed when a slot frees, SLOMissed when a completed
# request's TTFT/TPOT exceeded its tenant class target.
REASON_PREEMPTED = "RequestPreempted"
REASON_RESUMED = "RequestResumed"
REASON_SLO_MISSED = "SLOMissed"

# continuous profiler (obs/profiler.py): a jit program compiled OUTSIDE
# the warm_* window (and past the traffic grace) — the "cold mid-run
# compile polluted p95" bug class self-announces with the program name,
# the dispatch shape key, and the compile wall ms.
REASON_COMPILE_OBSERVED = "CompileObserved"

# crash-consistent recovery (docs/RECOVERY.md). CrashRecovered marks a
# restarted component adopting durable state a dead predecessor left
# mid-flight (also the epoch boundary `validate_events --epochs` splits
# chains on); OrphanReaped is the agent startup sweep releasing a
# device slice no CR epoch claims; MigrationAborted is the repacker
# watchdog rolling back a stuck migration; GrantDeadlineExceeded is the
# controller watchdog rolling back an allocation stuck in `creating`.
REASON_CRASH_RECOVERED = "CrashRecovered"
REASON_ORPHAN_REAPED = "OrphanReaped"
REASON_MIGRATION_ABORTED = "MigrationAborted"
REASON_GRANT_DEADLINE = "GrantDeadlineExceeded"

# fleet serving tier (serving/router.py + live KV session migration):
# a session exported off a replica (drain/rebalance) and the matching
# import+resume on its destination — both under the request's trace id
# so one trace shows the whole hop.
REASON_SESSION_EXPORTED = "SessionExported"
REASON_SESSION_IMPORTED = "SessionImported"

# fleet telemetry plane (obs/telemetry.py): multi-window SLO burn-rate
# monitor over federated attainment rollups. High fires when BOTH
# windows of a pair burn error budget faster than the pair's threshold;
# Cleared fires on the first evaluation after every pair recovers.
REASON_SLO_BURN_HIGH = "SLOBurnRateHigh"
REASON_SLO_BURN_CLEARED = "SLOBurnRateCleared"

# partition tolerance (docs/RECOVERY.md "Partitions & gray failures").
# ApiServerUnreachable marks a transport-level loss of the apiserver;
# DegradedModeEntered/Exited bracket an agent's static mode (keep
# realized slices serving, suppress mutations, reconcile durable truth
# on heal — `validate_events --nemesis` asserts the pairing);
# WriteFenced is a mutating commit refused because the writer's lease
# epoch went stale (a deposed, partitioned leader's in-flight batch);
# ReplicaEjected/ReplicaReadmitted bracket the router's gray-failure
# ejection of a slow-but-alive replica (latency EWMA past threshold)
# and its re-admission once the EWMA recovers.
REASON_APISERVER_UNREACHABLE = "ApiServerUnreachable"
REASON_DEGRADED_ENTERED = "DegradedModeEntered"
REASON_DEGRADED_EXITED = "DegradedModeExited"
REASON_WRITE_FENCED = "WriteFenced"
REASON_REPLICA_EJECTED = "ReplicaEjected"
REASON_REPLICA_READMITTED = "ReplicaReadmitted"

#: AllocationStatus value → the journal reason its transition records.
TRANSITION_REASONS = {
    "creating": REASON_SLICE_CREATING,
    "created": REASON_SLICE_CREATED,
    "ungated": REASON_SLICE_UNGATED,
    "failed": REASON_SLICE_FAILED,
    "deleted": REASON_SLICE_DELETED,
}

#: Every reason the journal accepts without a drift warning — the
#: doc-drift test asserts each appears in docs/OBSERVABILITY.md.
EVENT_REASONS = frozenset({
    REASON_SLICE_CREATING, REASON_SLICE_CREATED, REASON_SLICE_UNGATED,
    REASON_SLICE_FAILED, REASON_SLICE_DELETED,
    REASON_ADMITTED, REASON_PLACED, REASON_NO_CAPACITY, REASON_REJECTED,
    REASON_RETRYING, REASON_UNGATED, REASON_DEGRADED, REASON_HEALED,
    REASON_HEALTH_EVICTED,
    REASON_REPACK_PLANNED, REASON_REPACK_MIGRATING, REASON_REPACK_DONE,
    REASON_REPACK_FAILED,
    REASON_REALIZED, REASON_REALIZE_FAILED, REASON_TORN_DOWN,
    REASON_CHIP_UNHEALTHY, REASON_CHIP_HEALED,
    REASON_BREAKER_OPEN, REASON_BACKOFF, REASON_WATCH_RECONNECT,
    REASON_DRAIN_BEGIN, REASON_DRAIN_END, REASON_SHED, REASON_DRAINED,
    REASON_PREEMPTED, REASON_RESUMED, REASON_SLO_MISSED,
    REASON_COMPILE_OBSERVED,
    REASON_SESSION_EXPORTED, REASON_SESSION_IMPORTED,
    REASON_SLO_BURN_HIGH, REASON_SLO_BURN_CLEARED,
    REASON_CRASH_RECOVERED, REASON_ORPHAN_REAPED,
    REASON_MIGRATION_ABORTED, REASON_GRANT_DEADLINE,
    REASON_APISERVER_UNREACHABLE, REASON_DEGRADED_ENTERED,
    REASON_DEGRADED_EXITED, REASON_WRITE_FENCED,
    REASON_REPLICA_EJECTED, REASON_REPLICA_READMITTED,
})

# ------------------------------------------------------- labels / leases

#: Handoff ConfigMap owner label (garbage collection + discovery).
POD_UID_LABEL = f"{GROUP}/pod-uid"

#: Sub-second lease durations for the leader election (the integer
#: ``spec.leaseDurationSeconds`` field truncates; see utils/election.py).
LEASE_DURATION_MS_ANNOTATION = f"{GROUP}/lease-duration-ms"

#: Lease-epoch write fencing (docs/RECOVERY.md "Partitions & gray
#: failures"): every mutating commit from a leader-fenced component is
#: stamped with the writer's lease epoch (the Lease's monotonically
#: increasing ``leaseTransitions`` at acquisition), so the journal and
#: the CR itself record WHICH leadership term landed each write.
WRITER_EPOCH_ANNOTATION = f"{GROUP}/writer-epoch"
