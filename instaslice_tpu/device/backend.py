"""Backend interface + inventory/reservation models.

The reference's device API surface, reduced to what a TPU host actually
needs (SURVEY.md §2a): NVML's ``DeviceGetCount`` / profile enumeration /
``CreateGpuInstanceWithPlacement`` / ``CreateComputeInstance`` /
``Destroy`` become ``discover`` / ``reserve`` / ``release`` /
``list_reservations`` — on TPU the "create" step is an exclusive chip
reservation plus env computation, not a hardware partition call.
"""

from __future__ import annotations

import abc
import dataclasses
import os
from typing import Dict, List, Optional

from instaslice_tpu.topology.grid import Coord


class DeviceError(Exception):
    """Device-layer failure. The agent turns these into allocation
    status=failed (the reference logged and carried on —
    instaslice_daemonset.go:172-189, flagged in SURVEY.md §5)."""


class ChipsBusy(DeviceError):
    """Requested chips overlap a live reservation."""


class SliceExists(DeviceError):
    """Slice uuid already reserved (idempotent-create signal)."""


class SliceNotFound(DeviceError):
    """Release of an unknown slice uuid."""


@dataclasses.dataclass(frozen=True)
class NodeInventory:
    """What discovery reports about this host (reference:
    ``discoverAvailableProfilesOnGpus`` building MigGPUUUID + Migplacement,
    instaslice_daemonset.go:588-664)."""

    generation: str                 # "v5e" ...
    chip_paths: Dict[int, str]      # local chip id → device path
    host_offset: Coord = (0, 0, 0)  # this host's corner in its torus group
    torus_group: str = ""           # shared physical-mesh id ("" = alone)
    source: str = "fake"            # "accel" | "vfio" | "fake" | ...

    @property
    def chip_count(self) -> int:
        return len(self.chip_paths)


@dataclasses.dataclass(frozen=True)
class Reservation:
    slice_uuid: str
    chip_ids: tuple                 # sorted local chip ids


class DeviceBackend(abc.ABC):
    """One node's device access. Implementations must be idempotent and
    restart-safe: ``list_reservations`` after a process restart must still
    report every live reservation (the reference's in-memory
    ``cachedPreparedMig`` map loses this — instaslice_daemonset.go:87-93)."""

    name: str = ""

    @abc.abstractmethod
    def discover(self) -> NodeInventory: ...

    @abc.abstractmethod
    def reserve(self, slice_uuid: str, chip_ids: List[int]) -> Reservation:
        """Exclusively reserve chips. Raises :class:`ChipsBusy` on overlap,
        :class:`SliceExists` if the uuid is already reserved."""

    @abc.abstractmethod
    def release(self, slice_uuid: str) -> None:
        """Raises :class:`SliceNotFound` for unknown uuids."""

    @abc.abstractmethod
    def list_reservations(self) -> List[Reservation]: ...

    def healthy(self) -> bool:
        try:
            self.list_reservations()
            return True
        except DeviceError:
            return False

    def chip_health(self) -> Dict[int, bool]:
        """Per-chip health: local chip id → healthy. Must cover the union
        of present chips and chips in live reservations — a reserved chip
        whose device node vanished (driver unbound a failed chip) is
        reported ``False``, not omitted. Empty dict = backend has no
        per-chip health signal (treated as all-healthy). The reference has
        no analog: SURVEY.md §5 flags "no health monitoring of slices" as
        a gap this rebuild must close."""
        return {}


class TracedBackend:
    """Span-emitting decorator for any :class:`DeviceBackend`: the
    state-changing device operations (discover/reserve/release) become
    ``device.<op>`` spans in the process tracer, inheriting the
    caller's ambient trace context — so a reserve issued inside the
    agent's ``agent.realize`` span (which is bound to the allocation's
    trace id) shows up as a child span of that grant's trace. The
    periodic read-only polls (``healthy``/``chip_health``/
    ``list_reservations``) are deliberately NOT spanned: they run every
    few seconds forever, and each would root a fresh single-span trace
    — flooding the span ring and any ``TPUSLICE_TRACE_FILE`` with
    noise unrelated to any grant. Exceptions pass through untouched
    (the span records them); unknown attributes (the untraced polls,
    backend-specific test helpers, ``name``) proxy to the inner
    backend, mirroring ``faults.FaultyBackend`` so the two wrappers
    stack in either order."""

    def __init__(self, inner: DeviceBackend) -> None:
        self._inner = inner

    def __getattr__(self, name):  # passthrough (test helpers included)
        return getattr(self._inner, name)

    def _traced(self, op: str, fn, **attrs):
        from instaslice_tpu.utils.trace import get_tracer

        with get_tracer().span(f"device.{op}", **attrs):
            return fn()

    def discover(self) -> NodeInventory:
        return self._traced("discover", self._inner.discover)

    def reserve(self, slice_uuid: str, chip_ids: List[int]) -> Reservation:
        return self._traced(
            "reserve",
            lambda: self._inner.reserve(slice_uuid, chip_ids),
            slice=slice_uuid, chips=len(chip_ids),
        )

    def release(self, slice_uuid: str) -> None:
        return self._traced(
            "release", lambda: self._inner.release(slice_uuid),
            slice=slice_uuid,
        )


def env_overrides() -> dict:
    """Topology hints the platform provides via env (GKE TPU node pools
    set these; tests set them explicitly):

    - ``TPUSLICE_GENERATION``: e.g. "v5e"
    - ``TPUSLICE_TORUS_GROUP``: physical-mesh id shared by co-torus hosts
    - ``TPUSLICE_HOST_OFFSET``: "x,y,z" of this host's corner
    """
    out: dict = {}
    if os.environ.get("TPUSLICE_GENERATION"):
        out["generation"] = os.environ["TPUSLICE_GENERATION"]
    if os.environ.get("TPUSLICE_TORUS_GROUP"):
        out["torus_group"] = os.environ["TPUSLICE_TORUS_GROUP"]
    off = os.environ.get("TPUSLICE_HOST_OFFSET")
    if off:
        parts = [int(v) for v in off.split(",")]
        if len(parts) != 3:
            raise DeviceError(
                f"TPUSLICE_HOST_OFFSET must be 'x,y,z', got {off!r}"
            )
        out["host_offset"] = tuple(parts)
    return out
