"""Backend auto-selection for the node agent and device plugin."""

from __future__ import annotations

import glob
import os

from instaslice_tpu.device.backend import DeviceBackend, DeviceError, env_overrides
from instaslice_tpu.device.fake import FakeTpuBackend
from instaslice_tpu.device.native import NativeBackend, find_library


def _chips_present(root: str = "") -> bool:
    return bool(
        glob.glob(os.path.join(root or "/", "dev", "accel[0-9]*"))
        or glob.glob(os.path.join(root or "/", "dev", "vfio", "[0-9]*"))
    )


def select_backend(kind: str = "auto", **kwargs) -> DeviceBackend:
    """``kind``: auto | fake | native | cloudtpu.

    ``auto`` picks native when libtpuslice.so and TPU device nodes are
    both present, else cloudtpu when a queued-resources endpoint is
    configured (``TPUSLICE_CLOUDTPU_API`` — the GKE/Cloud "driver",
    SURVEY.md §2a row 1), else fake (generation from
    TPUSLICE_GENERATION, default v5e) — so the same agent image runs on
    TPU metal, on GKE node pools, and in CI unchanged.
    """
    if kind == "native":
        return NativeBackend(**kwargs)
    if kind == "cloudtpu":
        from instaslice_tpu.device.cloudtpu import CloudTpuBackend

        return CloudTpuBackend(**kwargs)
    if kind == "fake":
        hints = env_overrides()
        kwargs.setdefault("generation", hints.get("generation", "v5e"))
        kwargs.setdefault("host_offset", hints.get("host_offset", (0, 0, 0)))
        kwargs.setdefault("torus_group", hints.get("torus_group", ""))
        return FakeTpuBackend(**kwargs)
    if kind == "auto":
        root = kwargs.pop("root", "")
        if find_library() and _chips_present(root):
            return NativeBackend(root=root, **kwargs)
        if os.environ.get("TPUSLICE_CLOUDTPU_API"):
            return select_backend("cloudtpu", **kwargs)
        return select_backend("fake", **kwargs)
    raise DeviceError(
        f"unknown backend kind {kind!r} (auto|fake|native|cloudtpu)"
    )
