"""Cloud TPU API (queued resources) device backend.

SURVEY.md §2a row 1 names the Cloud TPU queued-resources API as the
device "driver" on GKE/Cloud — the role the NVML calls play for the
reference on bare metal (``instaslice_daemonset.go``'s
CreateGpuInstanceWithPlacement / Destroy): where the native backend
reserves chips it can see under ``/dev``, this backend asks the cloud
control plane to provision them, with the CLOUD as the durable registry
(restart-safety for free — ``list_reservations`` is a server-side list,
not local state).

Wire surface (the v2 queued-resources REST shape, reduced to what the
agent uses):

- ``POST   {base}/projects/{p}/locations/{z}/queuedResources``
  ``?queued_resource_id={uuid}`` — create; the reserved chip ids ride in
  the node labels (``tpuslice-chips``), the slice uuid doubles as the
  queued-resource id.
- ``GET    .../queuedResources/{uuid}`` — poll the state machine
  (ACCEPTED → PROVISIONING → ACTIVE | FAILED).
- ``GET    .../queuedResources`` — list (rebuilds reservations).
- ``DELETE .../queuedResources/{uuid}`` — release.

Mapped errors: duplicate queued_resource_id → 409/alreadyExists →
:class:`SliceExists`; capacity conflict (the mock models it as a chip
overlap) → 409 → :class:`ChipsBusy`; unknown id → 404 →
:class:`SliceNotFound`; a resource that lands in FAILED is deleted
best-effort and surfaces as :class:`DeviceError` (the agent marks the
allocation ``failed`` and the controller retries elsewhere — same
contract as the native backend).

Auth is a bearer token (``TPUSLICE_CLOUDTPU_TOKEN``) — on GKE the
workload-identity metadata server would mint it; tests validate the
header end-to-end against the mock.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from instaslice_tpu.device.backend import (
    ChipsBusy,
    DeviceBackend,
    DeviceError,
    NodeInventory,
    Reservation,
    SliceExists,
    SliceNotFound,
    env_overrides,
)
from instaslice_tpu.topology.grid import get_generation

#: node label keys carrying the reservation through the cloud resource
CHIPS_LABEL = "tpuslice-chips"
UUID_LABEL = "tpuslice-uuid"

#: queued-resource states (the subset the backend reasons about)
_LIVE_STATES = frozenset(
    {"ACCEPTED", "PROVISIONING", "ACTIVE", "CREATING", "WAITING_FOR_RESOURCES"}
)


class CloudTpuBackend(DeviceBackend):
    name = "cloudtpu"

    def __init__(
        self,
        api_base: Optional[str] = None,
        project: Optional[str] = None,
        zone: Optional[str] = None,
        generation: Optional[str] = None,
        chip_count: Optional[int] = None,
        token: Optional[str] = None,
        poll_interval: float = 0.05,
        provision_timeout: float = 30.0,
        **hints,
    ) -> None:
        self.api_base = (api_base or os.environ.get("TPUSLICE_CLOUDTPU_API",
                                                    "")).rstrip("/")
        if not self.api_base:
            raise DeviceError(
                "cloudtpu backend needs an API endpoint "
                "(TPUSLICE_CLOUDTPU_API or api_base=)"
            )
        self.project = project or os.environ.get(
            "TPUSLICE_CLOUDTPU_PROJECT", "proj"
        )
        self.zone = zone or os.environ.get(
            "TPUSLICE_CLOUDTPU_ZONE", "zone-a"
        )
        env = env_overrides()
        self.generation = generation or env.get("generation", "v5e")
        gen = get_generation(self.generation)
        self._n = gen.chips_per_host if chip_count is None else chip_count
        self._host_offset = hints.get(
            "host_offset", env.get("host_offset", (0, 0, 0))
        )
        self._torus_group = hints.get(
            "torus_group", env.get("torus_group", "")
        )
        self.token = token or os.environ.get("TPUSLICE_CLOUDTPU_TOKEN", "")
        self.poll_interval = poll_interval
        self.provision_timeout = provision_timeout

    # ------------------------------------------------------------ HTTP

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _url(self, name: str = "", query: str = "") -> str:
        url = f"{self.api_base}/{self._parent}/queuedResources"
        if name:
            url += f"/{name}"
        if query:
            url += f"?{query}"
        return url

    def _call(self, method: str, url: str,
              body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode() or "{}")
            except ValueError:
                payload = {}
            err = payload.get("error", {})
            raise _ApiHttpError(
                e.code, err.get("status", ""), err.get("message", str(e))
            ) from None
        except urllib.error.URLError as e:
            raise DeviceError(
                f"cloudtpu API unreachable at {self.api_base}: {e.reason}"
            ) from None

    # ------------------------------------------------------- DeviceBackend

    def discover(self) -> NodeInventory:
        # provisioning is cloud-side: the "paths" identify chips within
        # this node's accelerator config, not /dev nodes
        return NodeInventory(
            generation=self.generation,
            chip_paths={
                i: f"cloudtpu://{self._parent}/chip{i}"
                for i in range(self._n)
            },
            host_offset=tuple(self._host_offset),
            torus_group=self._torus_group,
            source="cloudtpu",
        )

    def reserve(self, slice_uuid: str, chip_ids: List[int]) -> Reservation:
        if not slice_uuid:
            raise DeviceError("slice_uuid must be non-empty")
        if not chip_ids:
            raise DeviceError("chip_ids must be non-empty")
        unknown = [c for c in chip_ids if not 0 <= c < self._n]
        if unknown:
            raise DeviceError(
                f"chips {unknown} not on this host (have 0..{self._n - 1})"
            )
        chips = tuple(sorted(set(chip_ids)))
        body = {
            "tpu": {
                "nodeSpec": [{
                    "parent": self._parent,
                    "nodeId": f"tpuslice-{slice_uuid}",
                    "node": {
                        "acceleratorType": self.generation,
                        "labels": {
                            UUID_LABEL: slice_uuid,
                            CHIPS_LABEL: "_".join(map(str, chips)),
                        },
                    },
                }],
            },
        }
        try:
            self._call(
                "POST", self._url(query=f"queued_resource_id={slice_uuid}"),
                body,
            )
        except _ApiHttpError as e:
            if e.code == 409 and e.status == "ALREADY_EXISTS":
                raise SliceExists(
                    f"queued resource {slice_uuid} already exists"
                ) from None
            if e.code == 409:
                raise ChipsBusy(e.message) from None
            raise DeviceError(
                f"queued-resource create failed ({e.code}): {e.message}"
            ) from None
        self._await_active(slice_uuid)
        return Reservation(slice_uuid=slice_uuid, chip_ids=chips)

    def _await_active(self, slice_uuid: str) -> None:
        """Poll the queued-resource state machine to ACTIVE; a FAILED
        resource is deleted best-effort (so the uuid is reusable after
        the agent's retry) before the error surfaces."""
        deadline = time.monotonic() + self.provision_timeout
        while True:
            try:
                res = self._call("GET", self._url(slice_uuid))
            except _ApiHttpError as e:
                raise DeviceError(
                    f"queued resource {slice_uuid} vanished while "
                    f"provisioning ({e.code}): {e.message}"
                ) from None
            state = (res.get("state") or {}).get("state", "")
            if state == "ACTIVE":
                return
            if state in ("FAILED", "SUSPENDED"):
                try:
                    self.release(slice_uuid)
                except DeviceError:
                    pass
                raise DeviceError(
                    f"queued resource {slice_uuid} entered {state}: "
                    + (res.get("state") or {}).get("error", "no detail")
                )
            if time.monotonic() >= deadline:
                # same cleanup contract as FAILED: the agent saw this
                # reserve fail, so the resource must not stay live
                # (SliceExists on retry, chips leaked server-side)
                try:
                    self.release(slice_uuid)
                except DeviceError:
                    pass
                raise DeviceError(
                    f"queued resource {slice_uuid} not ACTIVE within "
                    f"{self.provision_timeout}s (state={state or '?'})"
                )
            # queued-resource provisioning poll: bounded by
            # provision_timeout above; the cloud API offers no event
            time.sleep(self.poll_interval)  # slicelint: disable=sleep-in-loop

    def release(self, slice_uuid: str) -> None:
        try:
            self._call("DELETE", self._url(slice_uuid))
        except _ApiHttpError as e:
            if e.code == 404:
                raise SliceNotFound(
                    f"no queued resource {slice_uuid}"
                ) from None
            raise DeviceError(
                f"queued-resource delete failed ({e.code}): {e.message}"
            ) from None

    def _list_raw(self) -> List[dict]:
        out = self._call("GET", self._url())
        return out.get("queuedResources", [])

    def list_reservations(self) -> List[Reservation]:
        res = []
        for qr in self._list_raw():
            state = (qr.get("state") or {}).get("state", "")
            if state not in _LIVE_STATES:
                continue
            labels = _node_labels(qr)
            uuid = labels.get(UUID_LABEL) or qr.get("name", "").split("/")[-1]
            chips_s = labels.get(CHIPS_LABEL, "")
            chips = tuple(
                int(c) for c in chips_s.split("_") if c
            ) if chips_s else ()
            res.append(Reservation(slice_uuid=uuid, chip_ids=chips))
        return sorted(res, key=lambda r: r.slice_uuid)

    def chip_health(self) -> Dict[int, bool]:
        """All configured chips healthy unless a queued resource holding
        them sits in FAILED — the cloud's signal that the underlying
        accelerators are bad."""
        health = {i: True for i in range(self._n)}
        for qr in self._list_raw():
            if (qr.get("state") or {}).get("state") != "FAILED":
                continue
            for c in _node_labels(qr).get(CHIPS_LABEL, "").split("_"):
                if c and int(c) in health:
                    health[int(c)] = False
        return health


class _ApiHttpError(Exception):
    def __init__(self, code: int, status: str, message: str):
        super().__init__(f"{code} {status}: {message}")
        self.code = code
        self.status = status
        self.message = message


def _node_labels(qr: dict) -> dict:
    specs = ((qr.get("tpu") or {}).get("nodeSpec")) or [{}]
    return (specs[0].get("node") or {}).get("labels") or {}
