"""ctypes binding over the C++ ``libtpuslice.so`` (see native/tpuslice/).

The production device path: real chip enumeration from /dev plus the
crash-safe flock'd reservation registry. Generation/topology metadata
comes from env (:func:`instaslice_tpu.device.backend.env_overrides`) since
the kernel driver does not expose ICI coordinates; on GKE the node pool
sets these.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import List, Optional

from instaslice_tpu.device.backend import (
    ChipsBusy,
    DeviceBackend,
    DeviceError,
    NodeInventory,
    Reservation,
    SliceExists,
    SliceNotFound,
    env_overrides,
)

_ERR = {
    -1: DeviceError,
    -2: DeviceError,
    -3: ChipsBusy,
    -4: SliceExists,
    -5: SliceNotFound,
    -6: DeviceError,
    -7: DeviceError,
}

_SEARCH_PATHS = [
    os.path.join(os.path.dirname(__file__), "libtpuslice.so"),
    os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "build",
        "libtpuslice.so",
    ),
    "/usr/local/lib/libtpuslice.so",
    "libtpuslice.so",
]


def find_library() -> Optional[str]:
    env = os.environ.get("TPUSLICE_LIBRARY")
    if env:
        return env if os.path.exists(env) else None
    for p in _SEARCH_PATHS:
        p = os.path.abspath(p)
        if os.path.exists(p):
            return p
    return None


class NativeBackend(DeviceBackend):
    name = "native"

    def __init__(
        self,
        library_path: Optional[str] = None,
        root: str = "",
        registry_dir: str = "",
        generation: str = "",
    ) -> None:
        path = library_path or find_library()
        if not path:
            raise DeviceError(
                "libtpuslice.so not found (build with `make -C native` or "
                "set TPUSLICE_LIBRARY)"
            )
        self._lib = ctypes.CDLL(path)
        self._lib.tpuslice_init.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        self._lib.tpuslice_discover.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        self._lib.tpuslice_reserve.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        self._lib.tpuslice_release.argtypes = [ctypes.c_char_p]
        self._lib.tpuslice_list.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        self._lib.tpuslice_health.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
        ]
        self._lib.tpuslice_strerror.argtypes = [ctypes.c_int]
        self._lib.tpuslice_strerror.restype = ctypes.c_char_p
        self._lib.tpuslice_version.restype = ctypes.c_char_p
        self._generation = generation
        self._check(
            self._lib.tpuslice_init(
                root.encode() or None, registry_dir.encode() or None
            ),
            "init",
        )

    def _check(self, rc: int, op: str) -> None:
        if rc == 0:
            return
        msg = self._lib.tpuslice_strerror(rc).decode()
        raise _ERR.get(rc, DeviceError)(f"tpuslice {op}: {msg}")

    def _call_json(self, fn, op: str, bufsize: int = 1 << 16) -> dict:
        buf = ctypes.create_string_buffer(bufsize)
        rc = fn(buf, len(buf))
        if rc == -7 and bufsize < (1 << 24):  # ERANGE: grow and retry
            return self._call_json(fn, op, bufsize * 8)
        self._check(rc, op)
        return json.loads(buf.value.decode())

    @property
    def version(self) -> str:
        return self._lib.tpuslice_version().decode()

    def discover(self) -> NodeInventory:
        d = self._call_json(self._lib.tpuslice_discover, "discover")
        hints = env_overrides()
        generation = self._generation or hints.get("generation", "")
        if not generation:
            raise DeviceError(
                "TPU generation unknown: set TPUSLICE_GENERATION or pass "
                "generation= (the kernel driver does not expose it)"
            )
        return NodeInventory(
            generation=generation,
            chip_paths={c["id"]: c["path"] for c in d["chips"]},
            host_offset=hints.get("host_offset", (0, 0, 0)),
            torus_group=hints.get("torus_group", ""),
            source=d["source"],
        )

    def reserve(self, slice_uuid: str, chip_ids: List[int]) -> Reservation:
        if not slice_uuid or not chip_ids:
            raise DeviceError("empty slice uuid or chip list")
        arr = (ctypes.c_int * len(chip_ids))(*chip_ids)
        self._check(
            self._lib.tpuslice_reserve(slice_uuid.encode(), arr, len(chip_ids)),
            "reserve",
        )
        return Reservation(
            slice_uuid=slice_uuid, chip_ids=tuple(sorted(chip_ids))
        )

    def release(self, slice_uuid: str) -> None:
        self._check(self._lib.tpuslice_release(slice_uuid.encode()), "release")

    def list_reservations(self) -> List[Reservation]:
        d = self._call_json(self._lib.tpuslice_list, "list")
        return [
            Reservation(slice_uuid=r["uuid"], chip_ids=tuple(r["chips"]))
            for r in d["reservations"]
        ]

    def chip_health(self) -> "dict[int, bool]":
        d = self._call_json(self._lib.tpuslice_health, "health")
        return {int(c["id"]): bool(c["healthy"]) for c in d["chips"]}
