"""Device layer: how the node agent touches (or fakes) TPU hardware.

Reference analog: the go-nvml / go-nvlib CGo layer
(``/root/reference/internal/controller/instaslice_daemonset.go:62-65``,
SURVEY.md §2a). Backends implement one interface so the agent is
unit-testable against the fake and identical in production:

- :class:`FakeTpuBackend` — the dgxa100-mock analog: synthetic chip
  inventory, failure injection, dangling-slice seeding.
- :class:`NativeBackend`  — ctypes over the C++ ``libtpuslice.so``:
  real chip enumeration plus a crash-safe flock'd reservation registry.
- :class:`CloudTpuBackend` — the GKE/Cloud "driver" (SURVEY.md §2a row
  1): chips provisioned through the Cloud TPU queued-resources REST
  API, with the cloud control plane as the durable registry.
- ``auto`` selection: native when the library and chips are present,
  cloudtpu when a queued-resources endpoint is configured, fake
  otherwise.
"""

from instaslice_tpu.device.backend import (
    DeviceBackend,
    DeviceError,
    ChipsBusy,
    NodeInventory,
    Reservation,
)
from instaslice_tpu.device.cloudtpu import CloudTpuBackend
from instaslice_tpu.device.fake import FakeTpuBackend
from instaslice_tpu.device.native import NativeBackend, find_library
from instaslice_tpu.device.select import select_backend
