"""Mock Cloud TPU queued-resources API server (for tests and demos).

The ``kube/httptest.py`` analog for the device layer's cloud driver:
:class:`CloudTpuBackend` pointed at this server exercises its full wire
path — URL building, auth header, JSON verbs, the provisioning state
machine, error mapping — without a GCP project. The server is
authoritative the way the real control plane is: duplicate
queued-resource ids and chip-capacity conflicts are rejected HERE,
atomically under one lock, so racing clients cannot double-grant.

State machine: a created resource advances ACCEPTED → PROVISIONING →
ACTIVE one step per GET poll (``provision_polls`` controls how many
PROVISIONING polls), or lands in FAILED when failure injection says so.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlsplit

from instaslice_tpu.device.cloudtpu import CHIPS_LABEL
from instaslice_tpu.utils.lockcheck import named_lock
from instaslice_tpu.utils.guards import guarded_by

_PATH = re.compile(
    r"^/projects/(?P<proj>[^/]+)/locations/(?P<zone>[^/]+)"
    r"/queuedResources(?:/(?P<name>[^/]+))?$"
)


class _QueuedResource:
    def __init__(self, name: str, body: dict, fail: bool,
                 provision_polls: int):
        self.name = name
        self.body = body
        self.fail = fail
        # remaining GET polls before ACTIVE (or FAILED): models the
        # cloud's async provisioning without wall-clock coupling
        self.polls_left = provision_polls
        self.state = "ACCEPTED"

    def poll(self) -> str:
        if self.state in ("ACTIVE", "FAILED"):
            return self.state
        if self.polls_left > 0:
            self.polls_left -= 1
            self.state = "PROVISIONING"
        else:
            self.state = "FAILED" if self.fail else "ACTIVE"
        return self.state

    def to_json(self, parent: str) -> dict:
        out = {
            "name": f"{parent}/queuedResources/{self.name}",
            "state": {"state": self.state},
            **self.body,
        }
        if self.state == "FAILED":
            out["state"]["error"] = "injected provisioning failure"
        return out


def _chips_of(body: dict) -> frozenset:
    specs = ((body.get("tpu") or {}).get("nodeSpec")) or [{}]
    labels = (specs[0].get("node") or {}).get("labels") or {}
    return frozenset(
        int(c) for c in labels.get(CHIPS_LABEL, "").split("_") if c
    )


class _Handler(BaseHTTPRequestHandler):
    server_state = None  # type: ignore[assignment]
    #: when set, requests must carry exactly this Bearer token or 401
    required_token: Optional[str] = None

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, status: str, message: str) -> None:
        self._send(code, {"error": {
            "code": code, "status": status, "message": message,
        }})

    def _authorized(self) -> bool:
        want = type(self).required_token
        if want is None:
            return True
        return self.headers.get("Authorization", "") == f"Bearer {want}"

    def _route(self):
        parts = urlsplit(self.path)
        m = _PATH.match(parts.path)
        if not m:
            self._error(404, "NOT_FOUND", f"no route {parts.path}")
            return None
        q = {k: v[0] for k, v in parse_qs(parts.query).items()}
        return m.group("proj"), m.group("zone"), m.group("name"), q

    def do_GET(self):
        if not self._authorized():
            return self._error(401, "UNAUTHENTICATED", "bad token")
        r = self._route()
        if r is None:
            return
        proj, zone, name, _ = r
        st = type(self).server_state
        parent = f"projects/{proj}/locations/{zone}"
        with st.lock:
            if name:
                qr = st.resources.get(name)
                if qr is None:
                    return self._error(
                        404, "NOT_FOUND", f"no queued resource {name}"
                    )
                qr.poll()
                return self._send(200, qr.to_json(parent))
            # list does NOT advance the state machine (a monitoring
            # list must not make provisioning complete faster)
            return self._send(200, {"queuedResources": [
                qr.to_json(parent)
                for qr in sorted(st.resources.values(),
                                 key=lambda q: q.name)
            ]})

    def do_POST(self):
        if not self._authorized():
            return self._error(401, "UNAUTHENTICATED", "bad token")
        r = self._route()
        if r is None:
            return
        proj, zone, _, q = r
        name = q.get("queued_resource_id", "")
        if not name:
            return self._error(
                400, "INVALID_ARGUMENT", "queued_resource_id required"
            )
        n = int(self.headers.get("Content-Length", "0") or 0)
        body = json.loads(self.rfile.read(n).decode() or "{}")
        st = type(self).server_state
        with st.lock:
            if name in st.resources:
                return self._error(
                    409, "ALREADY_EXISTS",
                    f"queued resource {name} already exists"
                )
            chips = _chips_of(body)
            for other in st.resources.values():
                if other.state == "FAILED":
                    continue
                overlap = chips & _chips_of(other.body)
                if overlap:
                    return self._error(
                        409, "RESOURCE_EXHAUSTED",
                        f"chips {sorted(overlap)} already reserved by "
                        f"{other.name}"
                    )
            fail = st.fail_next_creates > 0
            if fail:
                st.fail_next_creates -= 1
            st.resources[name] = _QueuedResource(
                name, body, fail, st.provision_polls
            )
            parent = f"projects/{proj}/locations/{zone}"
            return self._send(
                200, st.resources[name].to_json(parent)
            )

    def do_DELETE(self):
        if not self._authorized():
            return self._error(401, "UNAUTHENTICATED", "bad token")
        r = self._route()
        if r is None:
            return
        _, _, name, _ = r
        st = type(self).server_state
        with st.lock:
            if name not in st.resources:
                return self._error(
                    404, "NOT_FOUND", f"no queued resource {name}"
                )
            del st.resources[name]
        return self._send(200, {"done": True})


class CloudTpuMockServer:
    """The queued-resources API behind a real HTTP listener."""

    # shared between the test thread arming failures and the HTTP
    # handler threads consuming them
    fail_next_creates: guarded_by("device.cloudtpu_mock")

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 provision_polls: int = 1,
                 required_token: Optional[str] = None) -> None:
        self.lock = named_lock("device.cloudtpu_mock")
        self.resources: Dict[str, _QueuedResource] = {}
        self.provision_polls = provision_polls
        self.fail_next_creates = 0
        handler = type(
            "BoundHandler", (_Handler,),
            {"server_state": self, "required_token": required_token},
        )
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="cloudtpu-mock",
            daemon=True,
        )

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def fail_next_create(self, count: int = 1) -> None:
        """The next ``count`` created resources land in FAILED after
        provisioning (models the cloud failing to deliver capacity)."""
        with self.lock:
            self.fail_next_creates += count

    def start(self) -> "CloudTpuMockServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "CloudTpuMockServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
