"""Fake TPU backend — the dgxa100 mock-server analog (SURVEY.md §4 tier 1:
go-nvml ships a mock DGX-A100 and the reference's only real unit test
monkeypatches nvml onto it). This fake is richer: failure injection per
operation, dangling-slice seeding for adoption tests, call counting, and
optional persistence to survive simulated restarts.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from instaslice_tpu.device.backend import (
    ChipsBusy,
    DeviceBackend,
    DeviceError,
    NodeInventory,
    Reservation,
    SliceExists,
    SliceNotFound,
)
from instaslice_tpu.topology.grid import Coord, get_generation
from instaslice_tpu.utils.lockcheck import named_lock


class FakeTpuBackend(DeviceBackend):
    name = "fake"

    def __init__(
        self,
        generation: str = "v5e",
        host_offset: Coord = (0, 0, 0),
        torus_group: str = "",
        chip_count: Optional[int] = None,
    ) -> None:
        gen = get_generation(generation)
        n = gen.chips_per_host if chip_count is None else chip_count
        self._inventory = NodeInventory(
            generation=generation,
            chip_paths={i: f"/dev/accel{i}" for i in range(n)},
            host_offset=host_offset,
            torus_group=torus_group,
            source="fake",
        )
        self._lock = named_lock("device.fake")
        self._reservations: Dict[str, Tuple[int, ...]] = {}
        # failure injection: op name → remaining failures to inject
        self._fail: Dict[str, int] = {}
        self._failed_chips: set = set()
        self.calls: Dict[str, int] = {
            "discover": 0, "reserve": 0, "release": 0, "list": 0,
            "health": 0,
        }

    # ------------------------------------------------------------ test API

    def inject_failures(self, op: str, count: int = 1) -> None:
        """Make the next ``count`` calls of ``op`` raise DeviceError
        (op in discover|reserve|release|list)."""
        self._fail[op] = self._fail.get(op, 0) + count

    def fail_chip(self, chip_id: int) -> None:
        """Mark a chip unhealthy (ICI link down / driver unbind analog).
        Live reservations keep holding it; new reserves touching it fail."""
        with self._lock:
            self._failed_chips.add(chip_id)

    def heal_chip(self, chip_id: int) -> None:
        with self._lock:
            self._failed_chips.discard(chip_id)

    def seed_dangling(self, slice_uuid: str, chip_ids: List[int]) -> None:
        """Pre-existing slice for adoption tests (reference:
        ``discoverDanglingSlices``, instaslice_daemonset.go:666-748)."""
        with self._lock:
            self._reservations[slice_uuid] = tuple(sorted(chip_ids))

    def snapshot(self) -> Dict[str, Tuple[int, ...]]:
        with self._lock:
            return dict(self._reservations)

    def restore(self, snap: Dict[str, Tuple[int, ...]]) -> None:
        """Simulate agent restart against persisted device state."""
        with self._lock:
            self._reservations = dict(snap)

    def _maybe_fail(self, op: str) -> None:
        if self._fail.get(op, 0) > 0:
            self._fail[op] -= 1
            raise DeviceError(f"injected {op} failure")

    # ------------------------------------------------------------- backend

    def discover(self) -> NodeInventory:
        with self._lock:
            self.calls["discover"] += 1
            self._maybe_fail("discover")
            return self._inventory

    def reserve(self, slice_uuid: str, chip_ids: List[int]) -> Reservation:
        with self._lock:
            self.calls["reserve"] += 1
            self._maybe_fail("reserve")
            if not slice_uuid or not chip_ids:
                raise DeviceError("empty slice uuid or chip list")
            ids = tuple(sorted(chip_ids))
            if len(set(ids)) != len(ids):
                raise DeviceError(f"duplicate chip ids in {chip_ids}")
            for c in ids:
                if c not in self._inventory.chip_paths:
                    raise DeviceError(f"chip {c} not on this host")
            if slice_uuid in self._reservations:
                raise SliceExists(f"slice {slice_uuid} already reserved")
            taken = {c for r in self._reservations.values() for c in r}
            clash = [c for c in ids if c in taken]
            if clash:
                raise ChipsBusy(f"chips {clash} already reserved")
            dead = [c for c in ids if c in self._failed_chips]
            if dead:
                raise DeviceError(f"chips {dead} unhealthy")
            self._reservations[slice_uuid] = ids
            return Reservation(slice_uuid=slice_uuid, chip_ids=ids)

    def release(self, slice_uuid: str) -> None:
        with self._lock:
            self.calls["release"] += 1
            self._maybe_fail("release")
            if slice_uuid not in self._reservations:
                raise SliceNotFound(f"slice {slice_uuid} not reserved")
            del self._reservations[slice_uuid]

    def list_reservations(self) -> List[Reservation]:
        with self._lock:
            self.calls["list"] += 1
            self._maybe_fail("list")
            return [
                Reservation(slice_uuid=u, chip_ids=c)
                for u, c in sorted(self._reservations.items())
            ]

    def chip_health(self) -> Dict[int, bool]:
        with self._lock:
            self.calls["health"] += 1
            self._maybe_fail("health")
            ids = set(self._inventory.chip_paths)
            for r in self._reservations.values():
                ids.update(r)
            return {i: i not in self._failed_chips for i in sorted(ids)}
