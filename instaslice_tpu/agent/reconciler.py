"""Node-agent reconciler: realize ``creating`` allocations, tear down
``deleted`` ones.

Reference analog: the daemonset hot loop (``instaslice_daemonset.go:95-275``
— SURVEY.md §3.2/§3.3). Reference weaknesses deliberately fixed:

- device errors flip the allocation to ``failed`` instead of being logged
  and skipped (``:172-189``);
- idempotency comes from the CR's ``prepared`` records + the device
  registry, not an in-memory cache (``cachedPreparedMig``, ``:87-93``);
- capacity is advertised via a real patch-and-verify helper, not a
  label-toggle hack against an external device plugin (``:474-497``).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from instaslice_tpu import POD_RESOURCE_PREFIX
from instaslice_tpu.api.constants import (
    REASON_APISERVER_UNREACHABLE,
    REASON_CHIP_HEALED,
    REASON_CHIP_UNHEALTHY,
    REASON_DEGRADED_ENTERED,
    REASON_DEGRADED_EXITED,
    REASON_REALIZED,
    REASON_REALIZE_FAILED,
    REASON_TORN_DOWN,
)
from instaslice_tpu.faults import maybe_crash
from instaslice_tpu.obs.journal import emit_pod_event, get_journal
from instaslice_tpu.agent.discovery import discover_node
from instaslice_tpu.agent.handoff import configmap_manifest, slice_env
from instaslice_tpu.api import (
    AllocationDetails,
    AllocationStatus,
    PreparedDetails,
    PreparedPart,
    TpuSlice,
    slice_uuid_for,
)
from instaslice_tpu.device.backend import (
    ChipsBusy,
    DeviceBackend,
    DeviceError,
    SliceExists,
    SliceNotFound,
    TracedBackend,
)
from instaslice_tpu.kube.client import (
    AlreadyExists,
    KubeClient,
    NotFound,
    update_with_retry,
)
from instaslice_tpu.topology.grid import coord_to_id, get_generation
from instaslice_tpu.topology.placement import Box
from instaslice_tpu.utils.lockcheck import named_lock
from instaslice_tpu.utils.reconcile import Manager
from instaslice_tpu.utils.trace import get_tracer

log = logging.getLogger("instaslice_tpu.agent")


# slice_uuid_for moved to api.types (shared with the controller's
# occupancy computation); re-exported via the import above.


#: synthetic workqueue key driving the periodic chip-health sweep ("#" can
#: never collide with a node name)
HEALTH_KEY = "#health"


class NodeAgent:
    def __init__(
        self,
        client: KubeClient,
        backend: DeviceBackend,
        node_name: str,
        namespace: str = "instaslice-tpu-system",
        metrics=None,
        health_interval: float = 10.0,
        manager: Optional[Manager] = None,
    ) -> None:
        """``manager``: an externally-owned reconcile manager (the
        fleet-sim case — one sharded manager driving every node's agent
        logic, ``instaslice_tpu.sim.FleetAgents``). The agent then
        neither builds nor starts its own watch/worker threads; requeue
        re-adds ride the shared queue."""
        self.client = client
        # every device op this agent issues becomes a ``device.<op>``
        # span, joining whatever trace the agent has bound (the
        # allocation's trace id during realize/teardown)
        self.backend = (
            backend if isinstance(backend, TracedBackend)
            else TracedBackend(backend)
        )
        self.node_name = node_name
        self.namespace = namespace
        self.metrics = metrics
        self.health_interval = health_interval
        #: static/degraded mode (docs/RECOVERY.md "Partitions & gray
        #: failures"): set when the apiserver becomes unreachable at the
        #: transport level. Realized slices keep serving (the device
        #: plane needs no apiserver), every kube mutation is suppressed,
        #: and each requeue re-probes; the first successful probe runs a
        #: boot-style sweep against durable truth before reconciling.
        self.degraded = False
        self._degraded_lock = named_lock("agent.degraded")
        self.degraded_retry_s = 1.0
        self._owns_manager = manager is None
        self.manager = manager or Manager(
            name=f"agent-{node_name}",
            client=client,
            reconcile=self.reconcile,
            watches=[
                (
                    "TpuSlice",
                    namespace,
                    lambda ev, obj: [obj["metadata"]["name"]]
                    if obj["metadata"]["name"] == node_name
                    else [],
                )
            ],
        )

    # ---------------------------------------------------------------- boot

    def boot(self) -> TpuSlice:
        """Discovery + CR publication (SURVEY.md §3.4)."""
        return discover_node(
            self.client, self.backend, self.node_name, self.namespace
        )

    @property
    def tracer(self):
        # resolved per use, never cached at construction: after
        # reset_tracer() the agent's grant spans must land in the NEW
        # default tracer, not an orphaned closed ring
        return get_tracer()

    def start(self) -> None:
        self.boot()
        if not self._owns_manager:
            return  # fleet-managed: the shared manager drives us
        self.manager.start()
        self.manager.queue.add(self.node_name)
        if self.health_interval > 0:
            self.manager.queue.add(HEALTH_KEY, delay=self.health_interval)

    def stop(self) -> None:
        if self._owns_manager:
            self.manager.stop()

    # ----------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> Optional[float]:
        """Transport-aware wrapper: a connection-level apiserver failure
        anywhere in the reconcile flips the agent into static/degraded
        mode instead of crashing the loop; every requeue re-probes and
        the first success heals (boot-style sweep, then normal
        reconcile). Injected API errors (503s etc.) are NOT degraded
        triggers — they keep their existing retry semantics."""
        try:
            return self._reconcile_checked(key)
        except (ConnectionError, TimeoutError) as e:
            return self._enter_degraded(e)

    def _reconcile_checked(self, key: str) -> Optional[float]:
        if self.degraded:
            # any kube call below doubles as the heal probe; _heal
            # raises (→ _enter_degraded requeue) while still cut off
            self._heal()
        if key == HEALTH_KEY:
            return self._health_sweep()
        if key != self.node_name:
            return None
        try:
            obj = self.client.get("TpuSlice", self.namespace, key)
        except NotFound:
            return None
        ts = TpuSlice.from_manifest(obj)
        for alloc_id in sorted(ts.spec.allocations):
            alloc = ts.spec.allocations[alloc_id]
            if self.node_name not in alloc.parts:
                continue
            if (
                alloc.status == AllocationStatus.CREATING
                and self.node_name not in alloc.realized_on
            ):
                self._realize(ts, alloc)
            elif alloc.status == AllocationStatus.DELETED:
                self._teardown(ts, alloc)
        return None

    # ------------------------------------------------- degraded/static mode

    def _enter_degraded(self, exc: BaseException) -> float:
        """Record (once) that the apiserver is unreachable and schedule
        the re-probe. Journaling is local — the journal needs no
        apiserver."""
        with self._degraded_lock:
            first = not self.degraded
            self.degraded = True
        if first:
            log.warning(
                "%s: apiserver unreachable (%s); entering static mode — "
                "realized slices keep serving, mutations suppressed",
                self.node_name, exc,
            )
            j = get_journal()
            j.emit(
                f"agent-{self.node_name}",
                reason=REASON_APISERVER_UNREACHABLE,
                object_ref=f"node/{self.node_name}",
                message=f"apiserver unreachable: {exc}",
            )
            j.emit(
                f"agent-{self.node_name}",
                reason=REASON_DEGRADED_ENTERED,
                object_ref=f"node/{self.node_name}",
                message="static mode: serving frozen device state, "
                        "kube mutations suppressed",
            )
        return self.degraded_retry_s

    def _heal(self) -> None:
        """Probe the apiserver and, on success, leave degraded mode via
        a boot-style sweep (discovery + orphan reap against durable
        truth — the partition may have deleted allocations we still hold
        reservations for). Raises the transport error while the
        partition persists, which re-enters degraded mode upstream."""
        # boot() == discover_node: its first kube call is the probe, and
        # its sweep is exactly the restart reconciliation docs/RECOVERY.md
        # prescribes for rejoining a cluster whose state moved on
        self.boot()
        with self._degraded_lock:
            self.degraded = False
        log.info("%s: apiserver reachable again; leaving static mode",
                 self.node_name)
        get_journal().emit(
            f"agent-{self.node_name}",
            reason=REASON_DEGRADED_EXITED,
            object_ref=f"node/{self.node_name}",
            message="healed: boot sweep reconciled durable truth",
        )
        # the health sweep stopped publishing while degraded — catch up
        if self.health_interval > 0:
            self.manager.queue.add(HEALTH_KEY)

    # ------------------------------------------------------------- realize

    def _chip_ids_for(self, ts: TpuSlice, alloc: AllocationDetails) -> List[int]:
        gen = get_generation(ts.spec.generation)
        return alloc.local_chip_ids(self.node_name, gen.host_bounds)

    def _realize(self, ts: TpuSlice, alloc: AllocationDetails) -> None:
        with self.tracer.span(
            "agent.realize", trace_id=alloc.trace_id or None,
            node=self.node_name, alloc=alloc.alloc_id,
        ):
            self._realize_inner(ts, alloc)

    def _realize_inner(self, ts: TpuSlice, alloc: AllocationDetails) -> None:
        suid = slice_uuid_for(alloc.alloc_id, multihost=len(alloc.parts) > 1)
        chip_ids = self._chip_ids_for(ts, alloc)
        t0 = time.monotonic()
        try:
            # the backend is span-instrumented (TracedBackend): this
            # reserve shows up as a device.reserve child span of
            # agent.realize, in the allocation's trace
            self.backend.reserve(suid, chip_ids)
        except SliceExists:
            log.info("%s: reservation %s already live (idempotent)",
                     self.node_name, suid)
        except DeviceError as e:
            log.warning("%s: reserve %s failed: %s", self.node_name, suid, e)
            for pod in alloc.pods_on_node(self.node_name):
                emit_pod_event(
                    self.client, pod.namespace, pod.pod_name,
                    reason=REASON_REALIZE_FAILED,
                    message=f"{self.node_name}: chip reservation failed: {e}",
                    component=f"agent-{self.node_name}",
                    pod_uid=pod.pod_uuid, trace_id=alloc.trace_id,
                    event_type="Warning",
                )
            self._mark_failed(alloc.alloc_id, f"{self.node_name}: {e}")
            if self.metrics:
                self.metrics.device_errors.inc()
            return
        if self.metrics:
            self.metrics.reserve_seconds.observe(time.monotonic() - t0)
        # crash point (docs/RECOVERY.md): the chips are reserved on the
        # device but the CR knows nothing yet — a death here is what
        # the restart orphan sweep + the stuck-grant watchdog recover
        maybe_crash("agent.realize")

        # Device handoff + node pinning for every pod this node serves.
        for pod in alloc.pods_on_node(self.node_name):
            env = slice_env(alloc, pod, self.node_name, ts.spec.generation)
            cm = configmap_manifest(
                pod.handoff, pod.namespace, env, owner_pod_uid=pod.pod_uuid
            )
            try:
                self.client.create("ConfigMap", cm)
            except AlreadyExists:
                self.client.patch(
                    "ConfigMap", pod.namespace, pod.handoff,
                    {"data": env},
                )
            self._patch_node_capacity(pod.handoff, add=True)

        wid, local_key = alloc.parts[self.node_name]
        part = PreparedPart(
            node_name=self.node_name,
            worker_id=wid,
            local_box=local_key,
            chip_ids=chip_ids,
            device_handle=suid,
        )

        def mut(obj: dict) -> Optional[dict]:
            cur = TpuSlice.from_manifest(obj)
            a = cur.spec.allocations.get(alloc.alloc_id)
            if a is None or a.status not in (
                AllocationStatus.CREATING,
                AllocationStatus.CREATED,
            ):
                return None  # raced with delete/fail — leave it alone
            if self.node_name not in a.realized_on:
                a.realized_on.append(self.node_name)
            prep = cur.spec.prepared.get(suid)
            if prep is None:
                prep = PreparedDetails(
                    slice_uuid=suid,
                    pod_uuid=a.pods[0].pod_uuid if a.pods else "",
                    profile=a.profile,
                    box=a.box,
                    parts={},
                )
                cur.spec.prepared[suid] = prep
            elif not prep.pod_uuid:
                # a crashed-realize reservation the boot sweep adopted
                # as dangling: this IS its allocation — claim it so the
                # record stops reading as ownerless
                prep.pod_uuid = a.pods[0].pod_uuid if a.pods else ""
                prep.profile = a.profile
                prep.box = a.box
            prep.parts[self.node_name] = part
            # Note: the agent never flips CREATING→CREATED. Each agent
            # reports realized_on only in its own CR copy; the controller
            # aggregates the union across copies and owns the status
            # transition — otherwise no copy of a multi-host allocation
            # would ever look fully realized.
            return cur.to_manifest()

        update_with_retry(
            self.client, "TpuSlice", self.namespace, self.node_name, mut
        )
        log.info(
            "%s: realized %s (%s chips %s)",
            self.node_name, alloc.alloc_id, alloc.profile, chip_ids,
        )
        for pod in alloc.pods_on_node(self.node_name):
            emit_pod_event(
                self.client, pod.namespace, pod.pod_name,
                reason=REASON_REALIZED,
                message=(f"{self.node_name}: realized {alloc.profile} "
                         f"(chips {chip_ids})"),
                component=f"agent-{self.node_name}",
                pod_uid=pod.pod_uuid, trace_id=alloc.trace_id,
            )

    def _mark_failed(
        self,
        alloc_id: str,
        message: str,
        from_statuses=(AllocationStatus.CREATING,),
    ) -> None:
        def mut(obj: dict) -> Optional[dict]:
            cur = TpuSlice.from_manifest(obj)
            a = cur.spec.allocations.get(alloc_id)
            if a is None or a.status not in from_statuses:
                return None
            a.set_status(AllocationStatus.FAILED, message)
            return cur.to_manifest()

        update_with_retry(
            self.client, "TpuSlice", self.namespace, self.node_name, mut
        )

    # ------------------------------------------------------------ teardown

    def _teardown(self, ts: TpuSlice, alloc: AllocationDetails) -> None:
        with self.tracer.span(
            "agent.teardown", trace_id=alloc.trace_id or None,
            node=self.node_name, alloc=alloc.alloc_id,
        ):
            self._teardown_inner(ts, alloc)

    def _teardown_inner(self, ts: TpuSlice, alloc: AllocationDetails) -> None:
        suid = slice_uuid_for(alloc.alloc_id, multihost=len(alloc.parts) > 1)
        # Always attempt release, even when this node never made it into
        # realized_on: a reserve that succeeded right as the allocation
        # was deleted (raced mut returning None) would otherwise leak the
        # device reservation forever.
        try:
            self.backend.release(suid)
        except SliceNotFound:
            pass
        except DeviceError as e:
            log.warning(
                "%s: release %s failed: %s (will retry)",
                self.node_name, suid, e,
            )
            if self.metrics:
                self.metrics.device_errors.inc()
            self.manager.queue.add(self.node_name, delay=1.0)
            return
        # crash point (docs/RECOVERY.md): chips released, CR still
        # carries the DELETED record + our realized_on — the restart
        # re-drives this teardown and release() is idempotent
        maybe_crash("agent.teardown")
        for pod in alloc.pods_on_node(self.node_name):
            try:
                self.client.delete("ConfigMap", pod.namespace, pod.handoff)
            except NotFound:
                pass
            self._patch_node_capacity(pod.handoff, add=False)

        def mut(obj: dict) -> Optional[dict]:
            cur = TpuSlice.from_manifest(obj)
            a = cur.spec.allocations.get(alloc.alloc_id)
            if a is None:
                return None
            if self.node_name in a.realized_on:
                a.realized_on.remove(self.node_name)
            prep = cur.spec.prepared.get(suid)
            if prep is not None:
                prep.parts.pop(self.node_name, None)
                if not prep.parts:
                    del cur.spec.prepared[suid]
            if not a.realized_on:
                # last agent out erases the allocation record entirely
                # (reference: instaslice_daemonset.go:252-267)
                del cur.spec.allocations[alloc.alloc_id]
            return cur.to_manifest()

        update_with_retry(
            self.client, "TpuSlice", self.namespace, self.node_name, mut
        )
        log.info("%s: tore down %s", self.node_name, alloc.alloc_id)
        get_journal().emit(
            f"agent-{self.node_name}",
            reason=REASON_TORN_DOWN,
            object_ref=f"alloc/{alloc.alloc_id}",
            message=f"released {suid} on {self.node_name}",
            trace_id=alloc.trace_id,
        )

    # -------------------------------------------------------------- health

    def _health_sweep(self) -> float:
        """Periodic per-chip health check (no reference analog: SURVEY.md
        §5 — "no health monitoring of slices"). Publishes failed chip ids
        to ``status.unhealthyChips`` via the status subresource (a plain
        update would be silently dropped by a real apiserver once the CRD
        declares ``subresources.status``) and fails in-flight allocations
        touching dead chips. Degraded GRANTED slices are the controller's
        business: it has the cross-node view a multi-host slice needs
        (``controller/reconciler.py: _reconcile_slice_health``), and the
        status write below is exactly what wakes it up."""
        try:
            health = self.backend.chip_health()
        except DeviceError as e:
            log.warning("%s: chip health probe failed: %s",
                        self.node_name, e)
            if self.metrics:
                self.metrics.device_errors.inc()
            return self.health_interval
        failed = sorted(i for i, ok in health.items() if not ok)
        if self.metrics:
            self.metrics.unhealthy_chips.labels(
                node=self.node_name
            ).set(len(failed))

        try:
            ts = TpuSlice.from_manifest(
                self.client.get("TpuSlice", self.namespace, self.node_name)
            )
        except NotFound:
            return self.health_interval
        if sorted(ts.status.unhealthy_chips) != failed:
            try:
                self.client.patch_status(
                    "TpuSlice", self.namespace, self.node_name,
                    {"unhealthyChips": failed},
                )
            except NotFound:
                return self.health_interval
            get_journal().emit(
                f"agent-{self.node_name}",
                reason=(REASON_CHIP_UNHEALTHY if failed
                        else REASON_CHIP_HEALED),
                object_ref=f"node/{self.node_name}",
                message=(f"chips {failed} unhealthy" if failed
                         else "all chips healthy again"),
            )
        if not failed:
            return self.health_interval

        gen = get_generation(ts.spec.generation)
        failed_set = set(failed)
        for alloc_id in sorted(ts.spec.allocations):
            alloc = ts.spec.allocations[alloc_id]
            dead = failed_set.intersection(
                alloc.local_chip_ids(self.node_name, gen.host_bounds)
            )
            if not dead:
                continue
            if alloc.status in (
                AllocationStatus.CREATING,
                AllocationStatus.CREATED,
            ):
                msg = f"{self.node_name}: chips {sorted(dead)} unhealthy"
                log.warning("failing in-flight allocation %s: %s",
                            alloc_id, msg)
                self._mark_failed(
                    alloc_id, msg,
                    from_statuses=(
                        AllocationStatus.CREATING,
                        AllocationStatus.CREATED,
                    ),
                )
        return self.health_interval

    # ---------------------------------------------------------------- node

    def _patch_node_capacity(self, handoff_name: str, add: bool) -> None:
        """Advertise/remove the per-pod extended resource on the Node
        (reference: ``createInstaSliceResource`` /
        ``cleanUpInstaSliceResource``, instaslice_daemonset.go:277-300,
        415-440). The per-pod resource is what pins the pod to the node
        that realized its slice; named by the pod's handoff name (pod name,
        or the stable handoff-name annotation for template-managed pods)."""
        res = f"{POD_RESOURCE_PREFIX}{handoff_name}"
        val = "1" if add else None
        try:
            self.client.patch_status(
                "Node", "", self.node_name,
                {
                    "capacity": {res: val},
                    "allocatable": {res: val},
                },
            )
        except NotFound:
            # Node objects are optional in unit tests / fake clusters.
            log.debug("node %s absent; skipping capacity patch",
                      self.node_name)
