"""Node-agent process runner — the ``cmd/daemonset/main.go`` analog:
client resolution, device-backend selection, metrics server, health
probes, signal handling around the
:class:`~instaslice_tpu.agent.reconciler.NodeAgent` (reference wiring:
``cmd/daemonset/main.go:55-168``). No leader election: exactly one agent
runs per node (DaemonSet), each keyed to its own CR."""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

from instaslice_tpu.agent.reconciler import NodeAgent
from instaslice_tpu.device.backend import DeviceBackend
from instaslice_tpu.kube.client import KubeClient
from instaslice_tpu.metrics.metrics import (
    EventMetrics,
    OperatorMetrics,
    start_metrics_server,
)
from instaslice_tpu.obs import journal as obs_journal
from instaslice_tpu.utils.probes import ProbeServer

log = logging.getLogger("instaslice_tpu.agent.runner")


def _split_bind(bind_address: str) -> tuple:
    """(host, port) from ':8080' / '127.0.0.1:8080'. The host part is
    honored by the metrics server — the kube-rbac-proxy patch relies on a
    real 127.0.0.1 bind, not a cosmetic one."""
    host, _, port_s = bind_address.rpartition(":")
    try:
        return host, int(port_s)
    except ValueError:
        return host, 0


class AgentRunner:
    def __init__(
        self,
        client: KubeClient,
        backend: DeviceBackend,
        node_name: str,
        namespace: str = "instaslice-tpu-system",
        metrics_bind_address: str = ":8084",
        health_probe_bind_address: str = ":8085",
    ) -> None:
        self.metrics = OperatorMetrics()
        # the journal's event counters ride this process's /metrics
        # registry (tpuslice_events_total — docs/OBSERVABILITY.md);
        # detached again in run()'s shutdown path
        self._event_metrics = EventMetrics(registry=self.metrics.registry)
        obs_journal.attach_metrics(self._event_metrics)
        self.metrics_host, self.metrics_port = _split_bind(
            metrics_bind_address
        )
        self.probe_address = health_probe_bind_address
        self.agent = NodeAgent(
            client, backend, node_name, namespace, metrics=self.metrics
        )
        self._stop = threading.Event()
        self._ready = False
        self.probes: Optional[ProbeServer] = None

    @classmethod
    def from_args(cls, args) -> "AgentRunner":
        from instaslice_tpu.device.select import select_backend
        from instaslice_tpu.kube.real import build_client

        return cls(
            build_client(getattr(args, "kubeconfig", "")),
            select_backend(args.backend),
            node_name=args.node_name,
            namespace=args.namespace,
            metrics_bind_address=args.metrics_bind_address,
            health_probe_bind_address=args.health_probe_bind_address,
        )

    def stop(self, *_sig) -> None:
        self._stop.set()

    def run(self) -> int:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(levelname)s %(name)s %(message)s",
        )
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, self.stop)
            except ValueError:  # not the main thread (tests)
                pass
        self.probes = ProbeServer(
            self.probe_address, ready_check=lambda: self._ready
        ).start()
        start_metrics_server(
            self.metrics, self.metrics_port, host=self.metrics_host
        )
        self.agent.start()
        self._ready = True
        log.info("agent running (node=%s, backend=%s)",
                 self.agent.node_name, self.agent.backend.name)
        try:
            self._stop.wait()
        finally:
            # readiness drops FIRST (readyz -> 503 "draining") so the
            # Service routes around this replica while the agent's
            # reconcile/health loops wind down; liveness stays green
            if self.probes:
                self.probes.set_draining(True)
            self._ready = False
            self.agent.stop()
            if self.probes:
                self.probes.stop()
            obs_journal.detach_metrics(self._event_metrics)
        return 0
