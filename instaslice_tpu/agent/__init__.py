"""Per-node agent — reference analog: the privileged daemonset
(``/root/reference/internal/controller/instaslice_daemonset.go``).

Watches this node's ``TpuSlice`` CR, realizes ``creating`` allocations on
the device backend (exclusive chip reservation + ConfigMap env handoff +
node-capacity patch), tears down ``deleted`` ones, and performs boot-time
discovery (chip inventory, profile catalog, dangling-slice adoption).
"""

from instaslice_tpu.agent.handoff import slice_env, configmap_manifest
from instaslice_tpu.agent.discovery import discover_node
from instaslice_tpu.agent.reconciler import NodeAgent
