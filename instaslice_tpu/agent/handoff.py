"""Device handoff: the env a granted pod consumes via ``envFrom``.

Reference analog: ``createConfigMap`` publishing ``NVIDIA_VISIBLE_DEVICES``
/ ``CUDA_VISIBLE_DEVICES`` in a ConfigMap named after the pod
(``instaslice_daemonset.go:796-818``; consumer side
``samples/test-pod.yaml:17-19``). The TPU equivalent is the libtpu/JAX
topology environment: which local chips the pod may open, where its host
sits in the slice mesh, and who its peer workers are — exactly the
variables a GKE TPU node pool would set for a static slice, computed here
for a dynamic one (SURVEY.md §2b row 1).
"""

from __future__ import annotations

from typing import Dict, List

from instaslice_tpu.api.constants import POD_UID_LABEL
from instaslice_tpu.api.types import AllocationDetails, PodRef
from instaslice_tpu.topology.grid import Shape, get_generation
from instaslice_tpu.topology.placement import Box


def _csv(vals) -> str:
    return ",".join(str(v) for v in vals)


def slice_env(
    alloc: AllocationDetails,
    pod: PodRef,
    node_name: str,
    generation: str,
) -> Dict[str, str]:
    """Env for ``pod`` (worker ``pod.worker_id``) of ``alloc``.

    Multi-host note: peer addressing uses pod names; multi-host sample
    manifests set ``hostname:`` + ``subdomain:`` with a headless Service so
    these resolve over DCN (see samples/).
    """
    gen = get_generation(generation)
    node = alloc.node_for_worker(pod.worker_id)
    if node is None:
        raise ValueError(
            f"allocation {alloc.alloc_id} has no part serving worker "
            f"{pod.worker_id}"
        )
    wid, local_key = alloc.parts[node]
    local_box = Box.from_key(local_key)
    global_box = alloc.global_box()
    part_shape = local_box.shape
    # All parts share one shape (alignment guarantees whole-tile splits):
    # hosts along each axis = global extent / per-host extent.
    host_bounds: Shape = tuple(
        global_box.shape[i] // part_shape[i] for i in range(3)
    )  # type: ignore[assignment]
    chip_ids = _local_ids(local_box, gen.host_bounds)
    workers = sorted(alloc.pods, key=lambda p: p.worker_id)
    hostnames = _csv(p.pod_name for p in workers)

    env = {
        # --- libtpu topology (what jax.distributed / libtpu read) ---
        "TPU_WORKER_ID": str(pod.worker_id),
        "TPU_WORKER_HOSTNAMES": hostnames,
        "TPU_VISIBLE_CHIPS": _csv(chip_ids),
        "TPU_CHIPS_PER_HOST_BOUNDS": _csv(part_shape),
        "TPU_HOST_BOUNDS": _csv(host_bounds),
        # newer libtpu spellings of the same facts
        "TPU_CHIPS_PER_PROCESS_BOUNDS": _csv(part_shape),
        "TPU_PROCESS_BOUNDS": _csv(host_bounds),
        "CLOUD_TPU_TASK_ID": str(pod.worker_id),
        "TPU_SKIP_MDS_QUERY": "true",
        "TPU_ACCELERATOR_TYPE": f"{generation}-{alloc.profile.split('-', 1)[1]}"
        if "-" in alloc.profile
        else alloc.profile,
        # --- slice identity (observability + tpuslicectl) ---
        "TPU_SLICE_NAME": alloc.alloc_id,
        "TPU_SLICE_PROFILE": alloc.profile,
        "TPU_SLICE_BOX": alloc.box,
        "TPU_SLICE_NODE": node_name,
    }
    return env


def _local_ids(local_box: Box, host_bounds: Shape) -> List[int]:
    from instaslice_tpu.topology.grid import coord_to_id

    return sorted(coord_to_id(c, host_bounds) for c in local_box.coords())


def configmap_manifest(
    name: str, namespace: str, env: Dict[str, str], owner_pod_uid: str = ""
) -> dict:
    """ConfigMap named after the pod (reference convention), labeled for
    garbage collection and discovery."""
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {
                "app.kubernetes.io/managed-by": "instaslice-tpu",
                POD_UID_LABEL: owner_pod_uid,
            },
        },
        "data": dict(env),
    }
