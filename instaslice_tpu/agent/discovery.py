"""Boot-time node discovery — reference analog:
``discoverMigEnabledGpuWithSlices`` / ``discoverAvailableProfilesOnGpus`` /
``discoverDanglingSlices`` (``instaslice_daemonset.go:555-748``), which run
once per node (guarded by ``Status.Processed``) and create the per-node CR
named ``$NODE_NAME``.

Differences by design:
- the profile catalog is computed from generation topology constants, not
  queried per-device, so identical on every healthy node;
- dangling-slice adoption ALSO runs on every boot (not just first), so an
  agent restart re-syncs ``spec.prepared`` with the device registry — the
  reference's in-memory cache forgets (SURVEY.md §5 restart recovery).
"""

from __future__ import annotations

import logging
from typing import Optional, Set

from instaslice_tpu.api import (
    PreparedDetails,
    PreparedPart,
    TpuSlice,
    TpuSliceSpec,
)
from instaslice_tpu.api.constants import REASON_ORPHAN_REAPED
from instaslice_tpu.device.backend import (
    DeviceBackend,
    DeviceError,
    NodeInventory,
    SliceNotFound,
)
from instaslice_tpu.kube.client import KubeClient, NotFound, update_with_retry
from instaslice_tpu.obs.journal import get_journal
from instaslice_tpu.topology.grid import coord_to_id, get_generation, id_to_coord
from instaslice_tpu.topology.placement import Box
from instaslice_tpu.topology.profiles import profile_catalog

log = logging.getLogger("instaslice_tpu.agent")


def _owned_alloc_id(suid: str) -> Optional[str]:
    """The allocation id a ``slice_uuid_for``-shaped reservation handle
    derives from, or None for a foreign (non-instaslice) handle."""
    if suid.startswith("sl-mh-"):
        return suid[len("sl-mh-"):]
    if suid.startswith("sl-"):
        return suid[len("sl-"):]
    return None


def _dangling_box(chip_ids, host_bounds, offset=(0, 0, 0)) -> str:
    """Bounding box of an adopted reservation's chips. ``offset`` shifts
    host-local coords into global torus coords (PreparedDetails.box and
    AllocationDetails.box are always global; PreparedPart.local_box is
    host-local)."""
    coords = [id_to_coord(c, host_bounds) for c in chip_ids]
    lo = tuple(min(c[i] for c in coords) + offset[i] for i in range(3))
    hi = tuple(max(c[i] for c in coords) + 1 + offset[i] for i in range(3))
    return Box(lo, tuple(hi[i] - lo[i] for i in range(3))).key()  # type: ignore[arg-type]


def build_tpuslice(
    node_name: str,
    namespace: str,
    inv: NodeInventory,
    backend: DeviceBackend,
) -> TpuSlice:
    """Fresh CR content from a device inventory."""
    gen = get_generation(inv.generation)
    spec = TpuSliceSpec(
        generation=inv.generation,
        host_offset=inv.host_offset,
        torus_group=inv.torus_group or node_name,
        chips={str(i): p for i, p in sorted(inv.chip_paths.items())},
        profiles=[
            {"name": p.name, **p.attributes()}
            for p in profile_catalog(inv.generation)
        ],
    )
    ts = TpuSlice(name=node_name, namespace=namespace, spec=spec)
    _adopt_dangling(ts, backend, gen.host_bounds, node_name, inv.host_offset)
    ts.status.processed = True
    return ts


def _sweep_orphans(ts: TpuSlice, backend) -> Set[str]:
    """Restart reconciliation, device side (docs/RECOVERY.md): slice
    handles shaped like ours (``sl-``/``sl-mh-``) whose allocation id
    exists in NO CR epoch are orphans — a crashed agent reserved them
    (or a stale dangling adoption outlived its record) and the durable
    truth never claimed them. They are reaped (released + journaled
    ``OrphanReaped``), never adopted: adopting would strand the chips
    occupied forever with no owner to ever tear them down. Foreign
    handles keep the reference's adopt-as-dangling behavior — they are
    not ours to kill. Removes matching stale dangling prepared entries
    from ``ts`` in place; returns the orphan handle set (the caller
    releases them AFTER the CR write lands, so a lost write never
    races a freed device)."""
    orphans: Set[str] = set()
    for suid in list(ts.spec.prepared):
        prep = ts.spec.prepared[suid]
        aid = _owned_alloc_id(suid)
        if aid is None or prep.pod_uuid:
            continue
        if aid not in ts.spec.allocations:
            del ts.spec.prepared[suid]
            orphans.add(suid)
    try:
        reservations = backend.list_reservations()
    except DeviceError as e:
        log.warning("orphan sweep: list_reservations failed: %s", e)
        return orphans
    for r in reservations:
        aid = _owned_alloc_id(r.slice_uuid)
        if aid is not None and aid not in ts.spec.allocations:
            orphans.add(r.slice_uuid)
    return orphans


def _reap_orphans(backend, node_name: str, orphans: Set[str]) -> None:
    for suid in sorted(orphans):
        try:
            backend.release(suid)
        except SliceNotFound:
            pass  # stale prepared entry with no live reservation
        except DeviceError as e:
            # the next boot's sweep retries; the CR no longer counts
            # the chips, so worst case is a transiently over-reserved
            # device registry, never a double-placement
            log.warning("%s: orphan release %s failed: %s",
                        node_name, suid, e)
            continue
        get_journal().emit(
            f"agent-{node_name}",
            reason=REASON_ORPHAN_REAPED,
            object_ref=f"slice/{suid}",
            message=(f"released orphaned device slice {suid}: no CR "
                     "epoch claims it"),
        )
        log.warning("%s: reaped orphaned device slice %s", node_name,
                    suid)


def _adopt_dangling(ts, backend, host_bounds, node_name,
                    host_offset=(0, 0, 0), skip: Optional[Set[str]] = None,
                    ) -> None:
    """Device reservations with no prepared record become dangling
    prepared entries (podUUID="") so the placement engine counts their
    chips as occupied (reference: instaslice_controller.go:312-320).
    ``skip`` excludes orphans the restart sweep is about to reap."""
    known = {
        part.device_handle or uid
        for uid, p in ts.spec.prepared.items()
        for part in p.parts.values()
    } | set(ts.spec.prepared) | (skip or set())
    for r in backend.list_reservations():
        if r.slice_uuid in known:
            continue
        ts.spec.prepared[r.slice_uuid] = PreparedDetails(
            slice_uuid=r.slice_uuid,
            pod_uuid="",
            profile="",
            box=_dangling_box(r.chip_ids, host_bounds, host_offset),
            parts={
                node_name: PreparedPart(
                    node_name=node_name,
                    worker_id=0,
                    local_box=_dangling_box(r.chip_ids, host_bounds),
                    chip_ids=list(r.chip_ids),
                    device_handle=r.slice_uuid,
                )
            },
        )
        log.info(
            "adopted dangling reservation %s (chips %s)",
            r.slice_uuid, list(r.chip_ids),
        )


def discover_node(
    client: KubeClient,
    backend: DeviceBackend,
    node_name: str,
    namespace: str,
) -> TpuSlice:
    """Create or refresh this node's CR. Safe to run on every boot —
    and the restart-reconciliation entry point: device truth is swept
    against the CR's allocations, and orphaned slices (device has
    them, no CR epoch claims them) are reaped after the CR write
    lands (docs/RECOVERY.md)."""
    inv = backend.discover()
    fresh = build_tpuslice(node_name, namespace, inv, backend)
    orphans: Set[str] = set()
    try:
        client.get("TpuSlice", namespace, node_name)
    except NotFound:
        # NO sweep on the create path: a fresh CR carries no history,
        # so "no epoch claims it" is vacuous here — and the CR may be
        # missing because an operator deleted it under LIVE workloads
        # (etcd restore), where releasing their chips would turn a
        # control-plane object loss into data-plane disruption. Adopt
        # everything as dangling (the reference behavior); the NEXT
        # boot's refresh sweep reaps what still has no claiming epoch.
        created = client.create("TpuSlice", fresh.to_manifest())
        log.info(
            "created TpuSlice %s/%s: %d chips, %d profiles",
            namespace, node_name, inv.chip_count, len(fresh.spec.profiles),
        )
        return TpuSlice.from_manifest(created)

    def refresh(obj: dict) -> dict:
        ts = TpuSlice.from_manifest(obj)
        # inventory/catalog/topology refresh; allocations/prepared are the
        # controller's + steady-state reconciler's business
        ts.spec.generation = fresh.spec.generation
        ts.spec.host_offset = fresh.spec.host_offset
        ts.spec.torus_group = fresh.spec.torus_group
        ts.spec.chips = fresh.spec.chips
        ts.spec.profiles = fresh.spec.profiles
        hb = get_generation(inv.generation).host_bounds
        orphans.clear()
        orphans.update(_sweep_orphans(ts, backend))
        _adopt_dangling(ts, backend, hb, node_name, inv.host_offset,
                        skip=orphans)
        ts.status.processed = True
        return ts.to_manifest()

    out = update_with_retry(client, "TpuSlice", namespace, node_name, refresh)
    _reap_orphans(backend, node_name, orphans)
    return TpuSlice.from_manifest(out)
