"""Boot-time node discovery — reference analog:
``discoverMigEnabledGpuWithSlices`` / ``discoverAvailableProfilesOnGpus`` /
``discoverDanglingSlices`` (``instaslice_daemonset.go:555-748``), which run
once per node (guarded by ``Status.Processed``) and create the per-node CR
named ``$NODE_NAME``.

Differences by design:
- the profile catalog is computed from generation topology constants, not
  queried per-device, so identical on every healthy node;
- dangling-slice adoption ALSO runs on every boot (not just first), so an
  agent restart re-syncs ``spec.prepared`` with the device registry — the
  reference's in-memory cache forgets (SURVEY.md §5 restart recovery).
"""

from __future__ import annotations

import logging
from typing import Optional

from instaslice_tpu.api import (
    PreparedDetails,
    PreparedPart,
    TpuSlice,
    TpuSliceSpec,
)
from instaslice_tpu.device.backend import DeviceBackend, NodeInventory
from instaslice_tpu.kube.client import KubeClient, NotFound, update_with_retry
from instaslice_tpu.topology.grid import coord_to_id, get_generation, id_to_coord
from instaslice_tpu.topology.placement import Box
from instaslice_tpu.topology.profiles import profile_catalog

log = logging.getLogger("instaslice_tpu.agent")


def _dangling_box(chip_ids, host_bounds, offset=(0, 0, 0)) -> str:
    """Bounding box of an adopted reservation's chips. ``offset`` shifts
    host-local coords into global torus coords (PreparedDetails.box and
    AllocationDetails.box are always global; PreparedPart.local_box is
    host-local)."""
    coords = [id_to_coord(c, host_bounds) for c in chip_ids]
    lo = tuple(min(c[i] for c in coords) + offset[i] for i in range(3))
    hi = tuple(max(c[i] for c in coords) + 1 + offset[i] for i in range(3))
    return Box(lo, tuple(hi[i] - lo[i] for i in range(3))).key()  # type: ignore[arg-type]


def build_tpuslice(
    node_name: str,
    namespace: str,
    inv: NodeInventory,
    backend: DeviceBackend,
) -> TpuSlice:
    """Fresh CR content from a device inventory."""
    gen = get_generation(inv.generation)
    spec = TpuSliceSpec(
        generation=inv.generation,
        host_offset=inv.host_offset,
        torus_group=inv.torus_group or node_name,
        chips={str(i): p for i, p in sorted(inv.chip_paths.items())},
        profiles=[
            {"name": p.name, **p.attributes()}
            for p in profile_catalog(inv.generation)
        ],
    )
    ts = TpuSlice(name=node_name, namespace=namespace, spec=spec)
    _adopt_dangling(ts, backend, gen.host_bounds, node_name, inv.host_offset)
    ts.status.processed = True
    return ts


def _adopt_dangling(ts, backend, host_bounds, node_name,
                    host_offset=(0, 0, 0)) -> None:
    """Device reservations with no prepared record become dangling
    prepared entries (podUUID="") so the placement engine counts their
    chips as occupied (reference: instaslice_controller.go:312-320)."""
    known = {
        part.device_handle or uid
        for uid, p in ts.spec.prepared.items()
        for part in p.parts.values()
    } | set(ts.spec.prepared)
    for r in backend.list_reservations():
        if r.slice_uuid in known:
            continue
        ts.spec.prepared[r.slice_uuid] = PreparedDetails(
            slice_uuid=r.slice_uuid,
            pod_uuid="",
            profile="",
            box=_dangling_box(r.chip_ids, host_bounds, host_offset),
            parts={
                node_name: PreparedPart(
                    node_name=node_name,
                    worker_id=0,
                    local_box=_dangling_box(r.chip_ids, host_bounds),
                    chip_ids=list(r.chip_ids),
                    device_handle=r.slice_uuid,
                )
            },
        )
        log.info(
            "adopted dangling reservation %s (chips %s)",
            r.slice_uuid, list(r.chip_ids),
        )


def discover_node(
    client: KubeClient,
    backend: DeviceBackend,
    node_name: str,
    namespace: str,
) -> TpuSlice:
    """Create or refresh this node's CR. Safe to run on every boot."""
    inv = backend.discover()
    fresh = build_tpuslice(node_name, namespace, inv, backend)
    try:
        existing = client.get("TpuSlice", namespace, node_name)
    except NotFound:
        created = client.create("TpuSlice", fresh.to_manifest())
        log.info(
            "created TpuSlice %s/%s: %d chips, %d profiles",
            namespace, node_name, inv.chip_count, len(fresh.spec.profiles),
        )
        return TpuSlice.from_manifest(created)

    def refresh(obj: dict) -> dict:
        ts = TpuSlice.from_manifest(obj)
        # inventory/catalog/topology refresh; allocations/prepared are the
        # controller's + steady-state reconciler's business
        ts.spec.generation = fresh.spec.generation
        ts.spec.host_offset = fresh.spec.host_offset
        ts.spec.torus_group = fresh.spec.torus_group
        ts.spec.chips = fresh.spec.chips
        ts.spec.profiles = fresh.spec.profiles
        hb = get_generation(inv.generation).host_bounds
        _adopt_dangling(ts, backend, hb, node_name, inv.host_offset)
        ts.status.processed = True
        return ts.to_manifest()

    out = update_with_retry(client, "TpuSlice", namespace, node_name, refresh)
    return TpuSlice.from_manifest(out)
