"""Paged KV-cache accounting: a block pool with per-request block tables.

vLLM's PagedAttention insight, translated to this engine's TPU-first
layout: treat KV memory as a pool of fixed-size **token blocks** and
give every request a **block table** instead of a reserved
``max_len`` stripe. The wins are economic, not geometric —

- **admission control** keys on free *blocks*, not free stripes: a
  short request costs ``ceil(tokens / block_size)`` blocks, so mixed
  sequence lengths no longer reserve (and waste) the worst-case tail;
- **eviction frees blocks, not stripes**: a finished, shed, or
  preempted request's blocks return to the pool immediately and are
  admittable on the very next decode step;
- **preemption parks the table**: a preempted request keeps its blocks
  (its KV stripe is read out beside them), so resume is a stripe write
  — no re-prefill — while the *slot* goes back to the batch;
- **prefix sharing is copy-on-write**: a registered prefix's blocks
  are pinned read-only; a request admitted through a prefix hit (or a
  parallel-sampling fork) *references* them at zero pool cost until
  its first write into a shared block copies just that block.

One honest caveat, stated once: the engine's physical cache stays the
rectangular ``(L, max_batch, H, max_len, hd)`` array XLA compiles two
programs against — a live slot's KV is row-resident, not scattered.
The pool is therefore the serving plane's **accounting truth** (what
admission, preemption, utilization, and the ``tpuslice_kv_blocks_*``
gauges reason over), mapping logical blocks onto row extents the way
vLLM maps them onto physical pages. Everything here is pure host-side
bookkeeping — no jax, no device sync — and is exercised identically on
the driver and every op-stream follower.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


class BlockPoolExhausted(RuntimeError):
    """No free block: the caller must shed parked state (or refuse the
    admission) — the scheduler's headroom guard exists to make this
    unreachable on the decode path."""


@dataclasses.dataclass
class Block:
    """One fixed-size token block. ``refs`` counts the tables holding
    it (>1 = copy-on-write shared); ``pinned`` marks registered-prefix
    blocks, which live outside the allocatable pool and never return
    to the free list while their prefix is registered."""

    block_id: int
    refs: int = 1
    pinned: bool = False


class BlockTable:
    """One request's ordered block list plus its token count. Sharing
    state lives on the blocks themselves (``Block.refs``/``pinned``) —
    refcounts are the single source of truth for every copy-on-write
    decision (:meth:`KVBlockPool.ensure`), so the table carries no
    shadow counter that could drift stale when a co-sharer releases."""

    def __init__(self, blocks: Optional[List[Block]] = None,
                 tokens: int = 0) -> None:
        self.blocks: List[Block] = blocks or []
        self.tokens = tokens

    def __len__(self) -> int:
        return len(self.blocks)


class KVBlockPool:
    """Fixed pool of ``total_blocks`` blocks of ``block_size`` tokens.

    Thread model: owned by the one scheduler thread that owns the
    engine (like every other piece of engine state) — no locks.
    """

    def __init__(self, total_blocks: int, block_size: int) -> None:
        if total_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need total_blocks >= 1 and block_size >= 1, got "
                f"{total_blocks}/{block_size}"
            )
        self.total_blocks = total_blocks
        self.block_size = block_size
        self._next_id = 0
        #: blocks currently allocated from the pool (pinned excluded)
        self._allocated = 0
        #: registered-prefix blocks (outside the allocatable pool)
        self._pinned = 0
        # copy-on-write events since construction (observability)
        self.cow_copies = 0

    # ------------------------------------------------------------ internals

    def blocks_for(self, tokens: int) -> int:
        """Blocks covering ``tokens`` — THE ceiling-division everyone
        (engine admission math, scheduler headroom, stripe rounding)
        must share so accounting cannot drift from the allocator."""
        return -(-tokens // self.block_size) if tokens > 0 else 0

    def _new_block(self, pinned: bool = False) -> Block:
        if not pinned:
            if self._allocated >= self.total_blocks:
                raise BlockPoolExhausted(
                    f"kv block pool exhausted "
                    f"({self.total_blocks} blocks of {self.block_size})"
                )
            self._allocated += 1
        else:
            self._pinned += 1
        b = Block(self._next_id, pinned=pinned)
        self._next_id += 1
        return b

    def _drop_ref(self, block: Block) -> None:
        block.refs -= 1
        if block.refs == 0:
            if block.pinned:
                self._pinned -= 1
            else:
                self._allocated -= 1

    # -------------------------------------------------------------- queries

    def free_blocks(self) -> int:
        return self.total_blocks - self._allocated

    def used_blocks(self) -> int:
        return self._allocated

    def pinned_blocks(self) -> int:
        return self._pinned

    def can_allocate(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= self.free_blocks()

    # ----------------------------------------------------------- allocation

    def allocate(self, tokens: int) -> BlockTable:
        """A fresh table covering ``tokens`` (all blocks exclusive)."""
        need = self.blocks_for(tokens)
        if need > self.free_blocks():
            raise BlockPoolExhausted(
                f"need {need} blocks, {self.free_blocks()} free"
            )
        return BlockTable(
            [self._new_block() for _ in range(need)], tokens
        )

    def pin(self, tokens: int) -> BlockTable:
        """A registered prefix's table: pinned read-only blocks outside
        the allocatable pool (prefix stripes are separate HBM arrays,
        not slot rows — pinning them against the slot pool would shrink
        serving capacity the stripes never consumed)."""
        return BlockTable(
            [self._new_block(pinned=True)
             for _ in range(self.blocks_for(tokens))],
            tokens,
        )

    def fork(self, parent: BlockTable, tokens: Optional[int] = None) \
            -> BlockTable:
        """Share ``parent``'s blocks copy-on-write: the child references
        them (refcount++, zero pool cost) and copies lazily as it grows
        past — or writes into — the shared region. ``tokens`` trims the
        share to a prefix of the parent (a prefix hit shares only the
        matched tokens)."""
        t = parent.tokens if tokens is None else tokens
        n = self.blocks_for(t)
        shared = parent.blocks[:n]
        for b in shared:
            b.refs += 1
        return BlockTable(list(shared), t)

    def ensure(self, table: BlockTable, tokens: int) -> None:
        """Grow ``table`` to cover ``tokens``, copy-on-writing the
        boundary block when the growth writes into a block someone
        else still references.

        Only the boundary block ever needs copying: growth writes at
        positions >= ``table.tokens``, and every earlier block holds
        final tokens no one writes again. The check is refcount-driven
        (refs > 1, or a pinned read-only prefix block), so it covers
        both sides of a fork — the child growing past its share AND the
        parent growing while children still reference its boundary.
        Raises :class:`BlockPoolExhausted` with the table unchanged
        when the pool cannot cover the growth."""
        if tokens <= table.tokens:
            return
        cost = self.growth_cost(table, tokens)
        if cost > self.free_blocks():
            raise BlockPoolExhausted(
                f"need {cost} block(s), {self.free_blocks()} free"
            )
        boundary_idx = self._cow_boundary(table)
        if boundary_idx >= 0:
            old = table.blocks[boundary_idx]
            table.blocks[boundary_idx] = self._new_block()
            self._drop_ref(old)
            self.cow_copies += 1
        for _ in range(
            max(0, self.blocks_for(tokens) - len(table.blocks))
        ):
            table.blocks.append(self._new_block())
        table.tokens = tokens

    def _cow_boundary(self, table: BlockTable) -> int:
        """Index of the boundary block a growth past ``table.tokens``
        must copy (shared or pinned, partially filled), or -1."""
        if table.tokens % self.block_size and table.blocks:
            idx = self.blocks_for(table.tokens) - 1
            b = table.blocks[idx]
            if b.refs > 1 or b.pinned:
                return idx
        return -1

    def growth_cost(self, table: BlockTable, tokens: int) -> int:
        """Blocks :meth:`ensure` will pull from the pool to grow
        ``table`` to ``tokens`` — new blocks plus the boundary
        copy-on-write when the boundary is genuinely shared. THE cost
        model, shared with the scheduler's pre-decode headroom guard
        so the guard can never under-count what ensure() charges."""
        if tokens <= table.tokens:
            return 0
        grow = max(0, self.blocks_for(tokens) - len(table.blocks))
        return grow + (1 if self._cow_boundary(table) >= 0 else 0)

    def bump(self, table: BlockTable, tokens: int) -> bool:
        """Token-count-only growth: True when covering ``tokens`` needs
        NO allocator work — no new block and no shared boundary to
        copy — in which case the table is updated in place for free.
        THE incremental fast path of the engine's per-round
        ``_sync_tables``: most decode rounds grow a slot within its
        current tail block, and charging a full :meth:`ensure` walk
        (exhaustion check, boundary scan, append loop) per slot per
        round is exactly the post-readback host time the overlap seam
        wants thin. Callers fall back to :meth:`ensure` on False."""
        if tokens <= table.tokens:
            return True
        if self.growth_cost(table, tokens) != 0:
            return False
        table.tokens = tokens
        return True

    def release(self, table: BlockTable) -> None:
        """Return every block reference; shared blocks survive while
        another table (or the pinned prefix) still holds them."""
        for b in table.blocks:
            self._drop_ref(b)
        table.blocks = []
        table.tokens = 0

    # -------------------------------------------------------- observability

    def stats(self, tables: Optional[Dict[int, BlockTable]] = None) \
            -> dict:
        """Pool gauges: ``free``/``used`` from the allocator, ``cow`` =
        blocks currently shared by more than one holder (the dedup the
        copy-on-write machinery is preserving right now).

        One relaxation of the no-locks thread model: this read path is
        also served to HTTP stats threads, so every container is
        list()-snapshotted before iteration — the counts are a
        point-in-time approximation under concurrent mutation, never a
        'changed size during iteration' crash."""
        cow = 0
        if tables:
            seen = set()
            for t in list(tables.values()):
                for b in list(t.blocks):
                    if b.refs > 1 and b.block_id not in seen:
                        seen.add(b.block_id)
                        cow += 1
        return {
            "total": self.total_blocks,
            "free": self.free_blocks(),
            "used": self.used_blocks(),
            "pinned": self._pinned,
            "cow": cow,
            "cow_copies": self.cow_copies,
            "block_size": self.block_size,
        }

    def utilization(self, live_tokens: int) -> float:
        """True block occupancy: tokens resident / capacity of the
        blocks holding them — allocated AND pinned, because resident
        tokens include prefix-covered positions whose storage is the
        pinned blocks (counting those tokens against allocated-only
        capacity would saturate the gauge at 1.0 for any prefix-hit
        traffic). High under mixed sequence lengths, where the legacy
        stripe metric divides by the whole ``max_batch x max_len``
        rectangle."""
        cap = (self.used_blocks() + self._pinned) * self.block_size
        if cap <= 0:
            return 0.0
        return min(1.0, live_tokens / cap)
