"""Paged KV-cache accounting: a block pool with per-request block tables.

vLLM's PagedAttention insight, translated to this engine's TPU-first
layout: treat KV memory as a pool of fixed-size **token blocks** and
give every request a **block table** instead of a reserved
``max_len`` stripe. The wins are economic, not geometric —

- **admission control** keys on free *blocks*, not free stripes: a
  short request costs ``ceil(tokens / block_size)`` blocks, so mixed
  sequence lengths no longer reserve (and waste) the worst-case tail;
- **eviction frees blocks, not stripes**: a finished, shed, or
  preempted request's blocks return to the pool immediately and are
  admittable on the very next decode step;
- **preemption parks the table**: a preempted request keeps its blocks
  (its KV stripe is read out beside them), so resume is a stripe write
  — no re-prefill — while the *slot* goes back to the batch;
- **prefix sharing is copy-on-write**: a registered prefix's blocks
  are pinned read-only; a request admitted through a prefix hit (or a
  parallel-sampling fork) *references* them at zero pool cost until
  its first write into a shared block copies just that block.

One honest caveat, stated once: the engine's physical cache stays the
rectangular ``(L, max_batch, H, max_len, hd)`` array XLA compiles two
programs against — a live slot's KV is row-resident, not scattered.
The pool is therefore the serving plane's **accounting truth** (what
admission, preemption, utilization, and the ``tpuslice_kv_blocks_*``
gauges reason over), mapping logical blocks onto row extents the way
vLLM maps them onto physical pages. Everything here is pure host-side
bookkeeping — no jax, no device sync — and is exercised identically on
the driver and every op-stream follower.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple


class BlockPoolExhausted(RuntimeError):
    """No free block: the caller must shed parked state (or refuse the
    admission) — the scheduler's headroom guard exists to make this
    unreachable on the decode path."""


@dataclasses.dataclass
class Block:
    """One fixed-size token block. ``refs`` counts the tables holding
    it (>1 = copy-on-write shared); ``pinned`` marks registered-prefix
    blocks, which live outside the allocatable pool and never return
    to the free list while their prefix is registered."""

    block_id: int
    refs: int = 1
    pinned: bool = False


class BlockTable:
    """One request's ordered block list plus its token count. Sharing
    state lives on the blocks themselves (``Block.refs``/``pinned``) —
    refcounts are the single source of truth for every copy-on-write
    decision (:meth:`KVBlockPool.ensure`), so the table carries no
    shadow counter that could drift stale when a co-sharer releases."""

    def __init__(self, blocks: Optional[List[Block]] = None,
                 tokens: int = 0) -> None:
        self.blocks: List[Block] = blocks or []
        self.tokens = tokens

    def __len__(self) -> int:
        return len(self.blocks)


class KVBlockPool:
    """Fixed pool of ``total_blocks`` blocks of ``block_size`` tokens.

    Thread model: owned by the one scheduler thread that owns the
    engine (like every other piece of engine state) — no locks.
    """

    def __init__(self, total_blocks: int, block_size: int) -> None:
        if total_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need total_blocks >= 1 and block_size >= 1, got "
                f"{total_blocks}/{block_size}"
            )
        self.total_blocks = total_blocks
        self.block_size = block_size
        self._next_id = 0
        #: blocks currently allocated from the pool (pinned excluded)
        self._allocated = 0
        #: registered-prefix blocks (outside the allocatable pool)
        self._pinned = 0
        # copy-on-write events since construction (observability)
        self.cow_copies = 0

    # ------------------------------------------------------------ internals

    def blocks_for(self, tokens: int) -> int:
        """Blocks covering ``tokens`` — THE ceiling-division everyone
        (engine admission math, scheduler headroom, stripe rounding)
        must share so accounting cannot drift from the allocator."""
        return -(-tokens // self.block_size) if tokens > 0 else 0

    def _new_block(self, pinned: bool = False) -> Block:
        if not pinned:
            if self._allocated >= self.total_blocks:
                raise BlockPoolExhausted(
                    f"kv block pool exhausted "
                    f"({self.total_blocks} blocks of {self.block_size})"
                )
            self._allocated += 1
        else:
            self._pinned += 1
        b = Block(self._next_id, pinned=pinned)
        self._next_id += 1
        return b

    def _drop_ref(self, block: Block) -> None:
        block.refs -= 1
        if block.refs == 0:
            if block.pinned:
                self._pinned -= 1
            else:
                self._allocated -= 1

    # -------------------------------------------------------------- queries

    def free_blocks(self) -> int:
        return self.total_blocks - self._allocated

    def used_blocks(self) -> int:
        return self._allocated

    def pinned_blocks(self) -> int:
        return self._pinned

    def can_allocate(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= self.free_blocks()

    # ----------------------------------------------------------- allocation

    def allocate(self, tokens: int) -> BlockTable:
        """A fresh table covering ``tokens`` (all blocks exclusive)."""
        need = self.blocks_for(tokens)
        if need > self.free_blocks():
            raise BlockPoolExhausted(
                f"need {need} blocks, {self.free_blocks()} free"
            )
        return BlockTable(
            [self._new_block() for _ in range(need)], tokens
        )

    def pin_block(self, block: Block) -> None:
        """Move an ALLOCATED block outside the allocatable pool
        (registration adopting an organically-cached radix path: its
        blocks become eviction-exempt, so leaving them counted as
        allocatable would silently shrink the capacity admission
        reasons over). No-op on already-pinned blocks; refcounts are
        untouched — only which ledger the block sits in changes."""
        if block.pinned:
            return
        block.pinned = True
        self._allocated -= 1
        self._pinned += 1

    def pin(self, tokens: int) -> BlockTable:
        """A fully-pinned table: read-only blocks outside the
        allocatable pool (pinned stripes are separate HBM arrays, not
        slot rows — charging them against the slot pool would shrink
        serving capacity they never consumed). Registered radix
        prefixes grow pinned via ``ensure(pinned=True)`` instead,
        because their tables also SHARE pool blocks with organic
        ancestors; this whole-table form remains the primitive for
        standalone pinned stripes."""
        return BlockTable(
            [self._new_block(pinned=True)
             for _ in range(self.blocks_for(tokens))],
            tokens,
        )

    def fork(self, parent: BlockTable, tokens: Optional[int] = None) \
            -> BlockTable:
        """Share ``parent``'s blocks copy-on-write: the child references
        them (refcount++, zero pool cost) and copies lazily as it grows
        past — or writes into — the shared region. ``tokens`` trims the
        share to a prefix of the parent (a prefix hit shares only the
        matched tokens)."""
        t = parent.tokens if tokens is None else tokens
        n = self.blocks_for(t)
        shared = parent.blocks[:n]
        for b in shared:
            b.refs += 1
        return BlockTable(list(shared), t)

    def ensure(self, table: BlockTable, tokens: int,
               pinned: bool = False) -> None:
        """Grow ``table`` to cover ``tokens``, copy-on-writing the
        boundary block when the growth writes into a block someone
        else still references.

        Only the boundary block ever needs copying: growth writes at
        positions >= ``table.tokens``, and every earlier block holds
        final tokens no one writes again. The check is refcount-driven
        (refs > 1, or a pinned read-only prefix block), so it covers
        both sides of a fork — the child growing past its share AND the
        parent growing while children still reference its boundary.
        Raises :class:`BlockPoolExhausted` with the table unchanged
        when the pool cannot cover the growth.

        ``pinned=True`` grows with PINNED blocks outside the
        allocatable pool (registered radix prefixes — registration
        must never shrink the capacity admission reasons over); the
        free-blocks check is skipped because nothing is drawn from the
        pool."""
        if tokens <= table.tokens:
            return
        if not pinned:
            cost = self.growth_cost(table, tokens)
            if cost > self.free_blocks():
                raise BlockPoolExhausted(
                    f"need {cost} block(s), {self.free_blocks()} free"
                )
        boundary_idx = self._cow_boundary(table)
        if boundary_idx >= 0:
            old = table.blocks[boundary_idx]
            table.blocks[boundary_idx] = self._new_block(pinned=pinned)
            self._drop_ref(old)
            self.cow_copies += 1
        for _ in range(
            max(0, self.blocks_for(tokens) - len(table.blocks))
        ):
            table.blocks.append(self._new_block(pinned=pinned))
        table.tokens = tokens

    def _cow_boundary(self, table: BlockTable) -> int:
        """Index of the boundary block a growth past ``table.tokens``
        must copy (shared or pinned, partially filled), or -1."""
        if table.tokens % self.block_size and table.blocks:
            idx = self.blocks_for(table.tokens) - 1
            b = table.blocks[idx]
            if b.refs > 1 or b.pinned:
                return idx
        return -1

    def growth_cost(self, table: BlockTable, tokens: int) -> int:
        """Blocks :meth:`ensure` will pull from the pool to grow
        ``table`` to ``tokens`` — new blocks plus the boundary
        copy-on-write when the boundary is genuinely shared. THE cost
        model, shared with the scheduler's pre-decode headroom guard
        so the guard can never under-count what ensure() charges."""
        if tokens <= table.tokens:
            return 0
        grow = max(0, self.blocks_for(tokens) - len(table.blocks))
        return grow + (1 if self._cow_boundary(table) >= 0 else 0)

    def bump(self, table: BlockTable, tokens: int) -> bool:
        """Token-count-only growth: True when covering ``tokens`` needs
        NO allocator work — no new block and no shared boundary to
        copy — in which case the table is updated in place for free.
        THE incremental fast path of the engine's per-round
        ``_sync_tables``: most decode rounds grow a slot within its
        current tail block, and charging a full :meth:`ensure` walk
        (exhaustion check, boundary scan, append loop) per slot per
        round is exactly the post-readback host time the overlap seam
        wants thin. Callers fall back to :meth:`ensure` on False."""
        if tokens <= table.tokens:
            return True
        if self.growth_cost(table, tokens) != 0:
            return False
        table.tokens = tokens
        return True

    def release(self, table: BlockTable) -> None:
        """Return every block reference; shared blocks survive while
        another table (or the pinned prefix) still holds them."""
        for b in table.blocks:
            self._drop_ref(b)
        table.blocks = []
        table.tokens = 0

    # -------------------------------------------------------- observability

    def stats(self, tables: Optional[Dict[int, BlockTable]] = None) \
            -> dict:
        """Pool gauges: ``free``/``used`` from the allocator, ``cow`` =
        blocks currently shared by more than one holder (the dedup the
        copy-on-write machinery is preserving right now).

        One relaxation of the no-locks thread model: this read path is
        also served to HTTP stats threads, so every container is
        list()-snapshotted before iteration — the counts are a
        point-in-time approximation under concurrent mutation, never a
        'changed size during iteration' crash."""
        cow = 0
        if tables:
            seen = set()
            for t in list(tables.values()):
                for b in list(t.blocks):
                    if b.refs > 1 and b.block_id not in seen:
                        seen.add(b.block_id)
                        cow += 1
        return {
            "total": self.total_blocks,
            "free": self.free_blocks(),
            "used": self.used_blocks(),
            "pinned": self._pinned,
            "cow": cow,
            "cow_copies": self.cow_copies,
            "block_size": self.block_size,
        }

    def utilization(self, live_tokens: int) -> float:
        """True block occupancy: tokens resident / capacity of the
        blocks holding them — allocated AND pinned, because resident
        tokens include prefix-covered positions whose storage is the
        pinned blocks (counting those tokens against allocated-only
        capacity would saturate the gauge at 1.0 for any prefix-hit
        traffic). High under mixed sequence lengths, where the legacy
        stripe metric divides by the whole ``max_batch x max_len``
        rectangle."""
        cap = (self.used_blocks() + self._pinned) * self.block_size
        if cap <= 0:
            return 0.0
        return min(1.0, live_tokens / cap)


# --------------------------------------------------------------- radix tree


def radix_granule(prefill_len: int, block_size: int) -> int:
    """THE radix-cache sharing granularity: node boundaries land on
    prefill-chunk boundaries so the remainder prefill after a hit
    reuses the one compiled program — i.e. the granule IS the prefill
    chunk. Block alignment is NOT required: node tables are full-
    prefix forks of their parent (position-exact by construction), so
    a granule smaller than a block just means the boundary block
    copy-on-writes like any other partial share. ``block_size`` is
    accepted for signature stability (earlier designs lcm'd it in)."""
    del block_size
    return prefill_len


class RadixNode:
    """One radix-tree node: an edge of whole granules, a FULL-PREFIX
    block table covering [0, end) built by forking the parent's table
    (shared blocks refcounted once — the "store any common prefix
    once" half of the tentpole), and the per-granule KV stripes the
    engine attaches (host-opaque here; device arrays in practice).

    ``owned`` is the deepest-creator attribution: the blocks THIS
    node's creation pulled (beyond its fork share of the parent, plus
    its boundary copy-on-write) — exactly what evicting it returns,
    because a request table referencing them always locks the path
    first. ``locks`` counts live/parked request tables whose prefix
    match runs through (or ends in) this node — a locked node is never
    evicted, so a parked request's table pins its tree path.
    ``registered`` marks operator-registered prefixes
    (:meth:`ServingEngine.register_prefix`): eviction-exempt until
    dropped. ``last_used`` is a LOGICAL clock tick (never wall time —
    op-stream followers must converge on identical eviction order)."""

    __slots__ = ("granules", "start", "table", "parent", "children",
                 "stripes", "draft_stripes", "locks", "registered",
                 "last_used", "owned")

    def __init__(self, granules: List[tuple], start: int,
                 table: BlockTable,
                 parent: Optional["RadixNode"]) -> None:
        self.granules = list(granules)
        self.start = start
        self.table = table
        self.parent = parent
        self.children: Dict[tuple, "RadixNode"] = {}
        #: engine-attached per-granule KV stripes, 1:1 with granules
        self.stripes: list = []
        self.draft_stripes: Optional[list] = None
        self.locks = 0
        self.registered = False
        self.last_used = 0
        #: blocks this node introduced (see class docstring)
        self.owned: List[Block] = []

    @property
    def end(self) -> int:
        return self.table.tokens

    def pool_block_count(self) -> int:
        """Pool (non-pinned) blocks attributed to this node — what
        evicting it returns to the allocator."""
        return sum(1 for b in self.owned if not b.pinned)


@dataclasses.dataclass
class RadixMatch:
    """A prefix match: the root-to-deepest chain of nodes whose
    granules the prompt walked, and the matched token count (granule-
    aligned; may end inside the deepest node's edge)."""

    path: List[RadixNode]
    length: int


class RadixIndex:
    """Radix/trie index over token sequences, granule-keyed, whose
    nodes own refcounted segment block tables in a :class:`KVBlockPool`
    — the global prefix cache's accounting + structure half (the engine
    owns the device stripes it hangs on the nodes).

    Same thread model as the pool: owned by the one scheduler thread
    that owns the engine. :meth:`match` and the gauge reads are PURE
    (no LRU touch, no clock tick) so the scheduler may call them while
    planning without diverging op-stream followers; every mutation
    (touch/lock/insert/evict) happens only inside engine ops that
    replay identically on every replica."""

    def __init__(self, pool: KVBlockPool, granule: int) -> None:
        if granule < 1:
            raise ValueError(f"granule must be >= 1, got {granule}")
        self.pool = pool
        self.granule = granule
        self.root = RadixNode([], 0, BlockTable(), None)
        #: logical LRU clock (ticks on touch/insert, never wall time)
        self.clock = 0
        #: nodes evicted since construction (observability)
        self.evictions = 0

    # -------------------------------------------------------------- queries

    def granules_of(self, tokens: List[int], limit: int) -> List[tuple]:
        """``tokens[:limit]`` cut into whole granules (limit floored)."""
        g = self.granule
        n = (min(limit, len(tokens)) // g) * g
        return [tuple(tokens[i:i + g]) for i in range(0, n, g)]

    def match(self, tokens: List[int], limit: int) -> RadixMatch:
        """Longest cached prefix of ``tokens[:limit]``, granule-exact.
        PURE — no LRU touch (scheduler planning calls this off the op
        stream; the admission op touches)."""
        want = self.granules_of(tokens, limit)
        path: List[RadixNode] = []
        node = self.root
        i = 0
        while i < len(want):
            child = node.children.get(want[i])
            if child is None:
                break
            k = 0
            while (k < len(child.granules) and i + k < len(want)
                   and child.granules[k] == want[i + k]):
                k += 1
            if k:
                path.append(child)
            i += k
            if k < len(child.granules):
                break
            node = child
        return RadixMatch(path, i * self.granule)

    def path_of(self, node: RadixNode) -> List[RadixNode]:
        """Root-to-node chain (root excluded)."""
        out: List[RadixNode] = []
        while node is not None and node is not self.root:
            out.append(node)
            node = node.parent
        out.reverse()
        return out

    def node_count(self) -> int:
        return sum(1 for _ in self._walk())

    def tokens_cached(self) -> int:
        """Distinct cached positions (each node's own span — full-
        prefix tables share everything above ``start``)."""
        return sum(n.end - n.start for n in self._walk())

    def pool_blocks(self) -> int:
        """Pool blocks the tree currently holds (pinned registered
        segments excluded) — the ``tpuslice_kv_blocks_prefix`` gauge."""
        return sum(n.pool_block_count() for n in self._walk())

    def evictable_blocks(self) -> int:
        """Pool blocks a full reclaim could free RIGHT NOW: the summed
        segments of every subtree containing no locked or registered
        node (leaf-first eviction removes exactly those). EXACT, not an
        estimate — segment tables are disjoint and a request table
        referencing a node always holds a lock on its path, so an
        unlocked subtree's blocks free at refcount 1. can_admit and the
        scheduler's headroom guard count these as available (the engine
        reclaims deterministically inside the admission op).

        Iterative post-order — this runs on every scheduler round and
        every can_admit, and with ``radix_decoded`` a long multi-turn
        conversation grows one deep chain (recursion would hit the
        interpreter limit exactly on the serving hot path)."""
        total = 0
        clear_of: Dict[int, bool] = {}
        stack: List[Tuple[RadixNode, bool]] = [(self.root, False)]
        while stack:
            node, expanded = stack.pop()
            if not expanded:
                stack.append((node, True))
                for c in list(node.children.values()):
                    stack.append((c, False))
                continue
            clear = node.locks == 0 and not node.registered
            for c in list(node.children.values()):
                clear = clear and clear_of.pop(id(c), False)
            if clear and node is not self.root:
                total += node.pool_block_count()
            clear_of[id(node)] = clear
        return total

    def _walk(self):
        stack = [self.root]
        while stack:
            n = stack.pop()
            # list() snapshot: /v1/stats walks the tree from HTTP
            # threads while the scheduler inserts/evicts
            stack.extend(list(n.children.values()))
            if n is not self.root:
                yield n

    # ------------------------------------------------------------ mutations

    def touch(self, node: RadixNode) -> None:
        """LRU-bump the node and its ancestors (one clock tick)."""
        self.clock += 1
        while node is not None and node is not self.root:
            node.last_used = self.clock
            node = node.parent

    def lock(self, node: RadixNode) -> None:
        while node is not None and node is not self.root:
            node.locks += 1
            node = node.parent

    def pin_path(self, node: RadixNode) -> int:
        """Move every pool block the root-to-``node`` path owns outside
        the allocatable pool (registration adopting organic nodes —
        the whole path is structurally un-evictable while the
        registered descendant lives, so its blocks must stop counting
        as reclaimable capacity). Returns blocks moved."""
        moved = 0
        for nd in self.path_of(node):
            for b in nd.owned:
                if not b.pinned:
                    self.pool.pin_block(b)
                    moved += 1
        return moved

    def unlock(self, node: RadixNode) -> None:
        while node is not None and node is not self.root:
            node.locks -= 1
            node = node.parent

    def ensure_path(self, granules: List[tuple]) \
            -> Tuple[RadixNode, int]:
        """Walk ``granules`` splitting edges so the matched boundary is
        an exact node end; returns (deepest matched node — the parent a
        new suffix child hangs under, root when nothing matched,
        matched granule count). Splits are pure host bookkeeping: the
        segment table and stripe list cut at the (block-aligned)
        granule boundary, no pool traffic, no device work."""
        node = self.root
        i = 0
        while i < len(granules):
            child = node.children.get(granules[i])
            if child is None:
                return node, i
            k = 0
            while (k < len(child.granules) and i + k < len(granules)
                   and child.granules[k] == granules[i + k]):
                k += 1
            i += k
            if k < len(child.granules):
                return self._split(child, k), i
            node = child
        return node, i

    def _split(self, node: RadixNode, k: int) -> RadixNode:
        """Split ``node``'s edge after ``k`` granules; returns the new
        upper node (which forks the shared head + takes the stripes
        and owned-block attribution inside its span — ``node`` object
        identity stays with the lower half, so held references and rid
        locks keep pointing at the deeper segment they matched
        through). Pure pool bookkeeping: the fork refcounts, no block
        moves, no device work."""
        mid = node.start + k * self.granule
        upper_table = self.pool.fork(node.table, mid)
        upper = RadixNode(node.granules[:k], node.start, upper_table,
                          node.parent)
        # deepest-creator attribution follows the split: blocks inside
        # the upper span re-attribute to the upper node, so evicting
        # any full unlocked subtree still frees exactly sum(owned)
        upper_ids = {b.block_id for b in upper_table.blocks}
        upper.owned = [b for b in node.owned
                       if b.block_id in upper_ids]
        node.owned = [b for b in node.owned
                      if b.block_id not in upper_ids]
        upper.stripes = node.stripes[:k]
        if node.draft_stripes is not None:
            upper.draft_stripes = node.draft_stripes[:k]
            node.draft_stripes = node.draft_stripes[k:]
        # a lock on the lower half pins the whole path; the new
        # ancestor must carry the same count or unlock would go negative
        upper.locks = node.locks
        upper.last_used = node.last_used
        upper.parent.children[upper.granules[0]] = upper
        upper.children[node.granules[k]] = node
        node.granules = node.granules[k:]
        node.stripes = node.stripes[k:]
        node.start = mid
        node.parent = upper
        return upper

    def add_child(self, parent: RadixNode, granules: List[tuple],
                  pinned: bool = False) -> RadixNode:
        """New node under ``parent``: its table forks the parent's
        full-prefix table (shared blocks stored once, refcounted) and
        grows to cover the new granules — pool blocks (organic,
        evictable) or pinned ones (registered prefixes live outside
        the allocatable pool, exactly like the pre-radix stripe cache,
        so registration never shrinks serving capacity). Raises
        :class:`BlockPoolExhausted` when the pool cannot cover an
        organic extension (callers skip the insert)."""
        if not granules:
            raise ValueError("add_child needs at least one granule")
        end = parent.end + len(granules) * self.granule
        table = self.pool.fork(parent.table, parent.end)
        had = {b.block_id for b in table.blocks}
        try:
            self.pool.ensure(table, end, pinned=pinned)
        except BlockPoolExhausted:
            self.pool.release(table)
            raise
        node = RadixNode(granules, parent.end, table, parent)
        node.owned = [b for b in table.blocks
                      if b.block_id not in had]
        self.clock += 1
        node.last_used = self.clock
        parent.children[granules[0]] = node
        return node

    def evict(self, node: RadixNode) -> int:
        """Remove an evictable leaf; returns the pool blocks freed
        (exactly the node's owned attribution — the lock discipline
        guarantees no request table still references them). The caller
        guarantees leaf + unlocked + unregistered."""
        freed = node.pool_block_count()
        self.pool.release(node.table)
        node.parent.children.pop(node.granules[0], None)
        node.parent = None
        node.stripes = []
        node.draft_stripes = None
        node.owned = []
        self.evictions += 1
        return freed

    def _lru_evictable_leaf(self) -> Optional[RadixNode]:
        best = None
        for n in self._walk():
            if n.children or n.locks > 0 or n.registered:
                continue
            key = (n.last_used, n.start, n.granules[0])
            if best is None or key < (best.last_used, best.start,
                                      best.granules[0]):
                best = n
        return best

    def hot_paths(self, max_paths: int = 32) -> List[List[str]]:
        """The most-recently-used root-to-leaf paths as granule-hash
        chains (:func:`granule_hash`) — the "advertised prefixes" half
        of the fleet router's shadow index. Hashes, not tokens: a
        ``/v1/stats`` poll must not ship prompt content across the
        fleet, and the router only needs equality at granule
        boundaries. list()-snapshotted like every stats walk."""
        leaves = [n for n in self._walk()
                  if not list(n.children.values())]
        leaves.sort(key=lambda n: n.last_used, reverse=True)
        out: List[List[str]] = []
        for leaf in leaves[:max_paths]:
            chain: List[str] = []
            for node in self.path_of(leaf):
                chain.extend(granule_hash(g) for g in node.granules)
            if chain:
                out.append(chain)
        return out

    def reclaim(self, need_blocks: int) -> int:
        """Evict LRU leaves (leaf-first — an interior node becomes a
        leaf once its children go) until ``need_blocks`` pool blocks
        came free or nothing evictable remains; returns blocks freed.
        Deterministic given tree state: called only inside engine ops,
        so op-stream followers evict the identical nodes."""
        freed = 0
        while freed < need_blocks:
            leaf = self._lru_evictable_leaf()
            if leaf is None:
                break
            freed += self.evict(leaf)
        return freed


# ------------------------------------------------------ session wire format
#
# The live-migration primitive's serialization half (docs/SERVING.md
# "Fleet router & session migration"): a preempted request's parked KV
# stripe (plus host decode state) crosses the DCN path between replicas
# as JSON — versioned, model-signature-checked at import, arrays carried
# as base64 rows. Pure host-side like everything else in this module:
# the codec speaks numpy buffers (the engine device_get/device_puts at
# its own seam), so op-stream followers replay imports byte-identically.

#: bump on ANY change to the blob layout the engine emits — import
#: REJECTS other versions outright (a half-understood session resumed
#: from a stale field set would silently corrupt the decode chain)
SESSION_WIRE_VERSION = 1


def granule_hash(granule) -> str:
    """Stable cross-process hash of one radix granule (a tuple of token
    ids) — the unit of the router's shadow prefix index. blake2b-8:
    Python's builtin ``hash`` is per-process salted and would make every
    replica advertise unmatchable chains."""
    raw = ",".join(str(int(t)) for t in granule).encode()
    return hashlib.blake2b(raw, digest_size=8).hexdigest()


def array_to_wire(arr) -> dict:
    """One numpy-like array → a JSON-safe dict (dtype/shape/b64 data)."""
    import numpy as np

    a = np.ascontiguousarray(arr)
    return {
        "__nd__": True,
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _wire_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 etc. live in ml_dtypes (always present beside jax)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def wire_to_array(obj: dict):
    import numpy as np

    raw = base64.b64decode(obj["data"])
    a = np.frombuffer(raw, dtype=_wire_dtype(obj["dtype"]))
    return a.reshape(obj["shape"]).copy()


def tree_to_wire(tree):
    """A pytree of arrays (dict / list / tuple nesting) → JSON-safe
    nesting. Tuples are tagged so the reconstruction round-trips the
    exact tree STRUCTURE — ``jax.tree.map`` over a cache and a stripe
    with list-vs-tuple drift would refuse to zip them."""
    if hasattr(tree, "dtype") and hasattr(tree, "shape"):
        return array_to_wire(tree)
    if isinstance(tree, dict):
        return {k: tree_to_wire(v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return {"__tuple__": [tree_to_wire(v) for v in tree]}
    if isinstance(tree, list):
        return [tree_to_wire(v) for v in tree]
    return tree


def wire_to_tree(obj):
    if isinstance(obj, dict):
        if obj.get("__nd__"):
            return wire_to_array(obj)
        if "__tuple__" in obj and len(obj) == 1:
            return tuple(wire_to_tree(v) for v in obj["__tuple__"])
        return {k: wire_to_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [wire_to_tree(v) for v in obj]
    return obj
