"""Multi-host serving smoke: the engine running SPMD over a DCN mesh.

Run inside every worker pod of a multi-host grant (or from the
two-process CPU test in ``tests/test_distributed.py``). Each worker:

1. parses the agent's handoff env and rendezvouses through
   :func:`initialize_distributed` (same seam as ``parallel/dcn_smoke``),
2. builds ONE global ``("model",)`` mesh over every process's devices —
   tensor parallelism spanning hosts: ICI within each host part, DCN
   between them,
3. builds an identical :class:`ServingEngine` over that mesh and runs an
   identical op sequence (admit → block decode). Multi-process JAX is
   SPMD: every process must execute the same jitted calls in the same
   order — exactly what the driver/follower op-stream
   (:mod:`instaslice_tpu.serving.distributed`) guarantees for live
   traffic; this smoke runs the static equivalent.

Every worker must print the SAME tokens (greedy, deterministic), and
they must equal the single-process reference for the same seed — a
wrong collective, a diverged op stream, or a non-replicated readback
all produce different tokens (or a distributed-runtime error).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    # one-claimant rule, resolved before the jax backend initializes:
    # CPU modes pin jax in-process; a TPU-bound run holds the host-wide
    # claim for its whole life (flock drops at process exit)
    from instaslice_tpu.utils.tpulock import TpuBusyError, claim_or_force_cpu

    n_local = int(os.environ.get("TPUSLICE_SMOKE_CPU_DEVICES", "0"))
    try:
        claim_or_force_cpu(force_cpu=bool(
            n_local or os.environ.get("TPUSLICE_SMOKE_FORCE_CPU")
        ))
    except TpuBusyError as e:
        print(json.dumps({"error": str(e)}))
        return 3

    import jax

    if n_local:
        try:
            jax.config.update("jax_num_cpu_devices", n_local)
        except AttributeError:
            # jax < 0.5: the XLA_FLAGS device-count path set by the
            # caller is the only knob
            pass

    import numpy as np
    from jax.sharding import Mesh

    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.parallel.meshenv import (
        SliceTopology,
        initialize_distributed,
    )
    from instaslice_tpu.serving import ServingEngine

    topo = SliceTopology.from_env()
    port = int(os.environ.get("TPUSLICE_SMOKE_PORT", "8477"))
    initialize_distributed(topo, port=port)

    devs = jax.devices()                      # global, post-rendezvous
    mesh = Mesh(np.array(devs), ("model",))

    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=len(devs), n_layers=2,
        d_ff=64, dtype=jax.numpy.float32, remat=False,
    )
    model = TpuLM(cfg)
    # oplog mode also replays a speculative round, so both replicas
    # need the (identical) self-draft wiring
    oplog = os.environ.get("TPUSLICE_SMOKE_MODE") == "oplog"
    eng = ServingEngine(
        model, max_batch=2, max_len=64, prefill_len=8, mesh=mesh,
        draft_model=model if oplog else None, spec_k=3,
    )
    result = {
        "worker_id": topo.worker_id,
        "processes_seen": len({d.process_index for d in devs}),
        "global_devices": len(devs),
    }

    if oplog:
        # dynamic traffic through the driver/follower op stream
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        oplog_port = int(os.environ["TPUSLICE_OPLOG_PORT"])
        if topo.worker_id == 0:
            deng = DistributedEngine(
                eng, n_followers=topo.num_workers - 1, port=oplog_port,
            )
            run_script(deng)
            deng.shutdown()
        else:
            # worker 0's hostname is the driver — the same coordinator
            # convention meshenv's rendezvous uses (on a real grant this
            # is worker 0's pod name over the headless Service)
            run_follower(eng, topo.hostnames[0], oplog_port)
        result["digest"] = state_digest(eng)
    else:
        # static op stream: every worker just runs the same sequence
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(8)
        result["tokens"] = [int(t) for t in out[rid]]

    print(json.dumps(result))
    return 0


def run_script(eng) -> None:
    """The dynamic driver script the test replays single-process:
    ragged admissions, block decodes, a speculative round (when the
    engine carries a draft), an external budget cut."""
    eng.add_request([5, 9, 2, 7])
    eng.decode_block(3)
    eng.add_request([11, 3], stop=None)        # admitted mid-flight
    eng.decode_block(3)
    if eng.draft_model is not None:
        eng.spec_step()                        # one speculative round
    # external budget cut of the first slot (slot 0), keep 4 tokens
    eng.finish_slot(0, n_keep=4)
    eng.decode_block(2)


def state_digest(eng) -> dict:
    """Engine-state fingerprint that must agree across all workers."""
    return {
        "finished": [
            [r.request_id, r.tokens, r.finished_reason]
            for r in eng.finished
        ],
        "live": {
            str(slot): req.generated
            for slot, req in sorted(eng.slots.items())
        },
        "tokens_generated": eng.tokens_generated,
    }


if __name__ == "__main__":
    sys.exit(main())
