"""On-device sampling filters: top-k and nucleus (top-p).

Pure, jit-friendly logit transforms shared by the engine's host-side
``_sample`` and the on-device block-decode scan — both paths must apply
the same filters or interactive and block decoding would sample from
different distributions.

TPU notes: ``top_k`` uses ``lax.top_k`` (no full sort); ``top_p`` sorts
the vocab once per step — a (B, V) descending sort is a cheap XLA sort
next to the decode matmuls, and everything stays static-shaped (the
nucleus boundary is a mask, never a dynamic slice).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# numpy, not jnp: a module-level jnp scalar would initialize the jax
# backend at import time (see parallel/ring.py)
_NEG = np.float32(-1e9)


def apply_repetition_penalty(
    logits: jax.Array, seen: jax.Array, penalty
) -> jax.Array:
    """HF/vLLM repetition penalty: for tokens already ``seen`` (…, V)
    bool (prompt + generated so far), positive logits divide by
    ``penalty`` and negative ones multiply — both push the token down
    for penalty > 1. Applied BEFORE temperature/filters (the HF order).
    ``penalty`` may be a traced scalar; callers skip the call entirely
    when the engine-level penalty is 1.0."""
    logits = logits.astype(jnp.float32)
    penalized = jnp.where(
        logits > 0, logits / penalty, logits * penalty
    )
    return jnp.where(seen, penalized, logits)


def filter_logits(
    logits: jax.Array, top_k: int = 0, top_p: float = 1.0,
    min_p: float = 0.0,
) -> jax.Array:
    """Mask ``logits`` (…, V) outside the top-k / nucleus / min-p set
    to -inf.

    ``top_k <= 0``, ``top_p >= 1`` and ``min_p <= 0`` are no-ops.
    ``top_p`` keeps the smallest set of tokens whose probabilities sum
    to at least ``top_p`` (the token crossing the threshold is kept,
    matching the standard nucleus-sampling definition). ``min_p`` keeps
    tokens whose probability is at least ``min_p`` × the top token's
    probability (the entropy-adaptive filter; the argmax always
    survives). Filters compose: top-k, then nucleus, then min-p, each
    over the previous survivors.
    """
    logits = logits.astype(jnp.float32)
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # drop tokens whose cumulative mass BEFORE them already reached
        # top_p (the crossing token stays); the argmax is NEVER dropped,
        # so a degenerate top_p <= 0 degrades to greedy rather than to
        # uniform-over-the-vocab garbage
        idx = jnp.arange(logits.shape[-1])
        drop_sorted = ((cum - probs) >= top_p) & (idx > 0)
        # threshold logit = smallest kept logit; everything below drops
        threshold = jnp.min(
            jnp.where(drop_sorted, jnp.inf, sorted_logits),
            axis=-1, keepdims=True,
        )
        logits = jnp.where(logits < threshold, _NEG, logits)
    if min_p > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        floor = min_p * jnp.max(probs, axis=-1, keepdims=True)
        # the argmax always survives (probs == floor when min_p == 1)
        logits = jnp.where(probs < floor, _NEG, logits)
    return logits


def speculative_accept(
    draft: jax.Array, q_probs: jax.Array, p_probs: jax.Array,
    rng: jax.Array,
):
    """Standard speculative-sampling acceptance (the
    draft-propose/target-verify accept-or-resample scheme): token i of
    each row's draft is accepted with probability ``min(1, p_i(x_i) /
    q_i(x_i))``; at the first rejection the replacement token is drawn
    from the normalized residual ``max(p_i - q_i, 0)``, and a fully
    accepted row draws its bonus token from ``p_k``. The emitted
    sequence is distributed EXACTLY as k+1 ancestral samples from
    ``p`` — losslessness does not depend on how good ``q`` is, only
    the acceptance rate does.

    ``draft`` (B, k) int tokens sampled from ``q_probs`` (B, k, V);
    ``p_probs`` (B, k+1, V) is the target distribution at every
    position (post temperature/top-k/top-p/min-p filtering — the
    distribution plain sampling draws from). Returns ``(accepted,
    out, logprobs, final)``: ``accepted`` (B,) in [0, k];
    ``out`` (B, k+1) holds the accepted draft prefix with the
    resampled/bonus token at index ``accepted`` (positions past it are
    unspecified — callers slice to ``accepted + 1``); ``logprobs`` is
    ``log p`` at each emitted position (the distribution the lossless
    output is distributed as); ``final`` (B,) = ``out[b, accepted]``.

    Pure and jit-friendly: all randomness derives from ``rng`` via
    ``fold_in``, so op-stream replicas replaying the same key converge
    on identical accepted counts.
    """
    B, k = draft.shape
    rows = jnp.arange(B)
    u = jax.random.uniform(jax.random.fold_in(rng, 0), (B, k))
    p_at = jnp.take_along_axis(
        p_probs[:, :k], draft[..., None], axis=-1
    )[..., 0]
    q_at = jnp.take_along_axis(q_probs, draft[..., None], axis=-1)[..., 0]
    # u * q < p  <=>  u < p/q where q > 0 (always: the draft sampled x
    # from q), without the divide-by-zero
    acc = (u * q_at < p_at).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(acc, axis=1), axis=1)       # (B,)
    # the token at index ``accepted``: residual distribution at a
    # rejection, plain p at full acceptance (q past the draft is 0)
    q_pad = jnp.concatenate(
        [q_probs, jnp.zeros_like(p_probs[:, :1])], axis=1
    )
    p_pos = p_probs[rows, accepted]                            # (B, V)
    q_pos = q_pad[rows, accepted]
    res = jnp.maximum(p_pos - q_pos, 0.0)
    norm = jnp.sum(res, axis=-1, keepdims=True)
    # p == q to machine precision leaves an all-zero residual; the
    # correct limit of norm(max(p - q, 0)) as q -> p is p itself
    res = jnp.where(norm > 0, res / jnp.where(norm > 0, norm, 1.0),
                    p_pos)
    final = jax.random.categorical(
        jax.random.fold_in(rng, 1),
        jnp.log(jnp.maximum(res, 1e-38)), axis=-1,
    ).astype(jnp.int32)
    out = jnp.concatenate(
        [draft.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    out = out.at[rows, accepted].set(final)
    logprobs = jnp.log(jnp.maximum(
        jnp.take_along_axis(p_probs, out[..., None], axis=-1)[..., 0],
        1e-38,
    ))
    return accepted, out, logprobs, final


def token_logprob(logits: jax.Array, toks: jax.Array) -> jax.Array:
    """log p(tok) under softmax(logits): logits (…, V), toks (…) int —
    returns (…) fp32. Callers pass the FILTERED/tempered logits so the
    probability is under the distribution actually sampled from."""
    return jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), toks[..., None], -1
    )[..., 0]
