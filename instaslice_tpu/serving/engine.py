"""Slot-based continuous-batching serving engine.

The vLLM-analog for this framework (the reference only ships
``samples/vllm_dep.yaml`` pointing vLLM at its MIG slice — SURVEY.md §1),
built TPU-first instead of translated:

- **Static shapes everywhere**: the decode step is one jitted call over a
  fixed (max_batch, 1) token tensor and a fixed-size KV cache — requests
  come and go by occupying/freeing *slots*, never by changing shapes, so
  XLA compiles exactly two programs (prefill, decode) regardless of
  traffic. This is the TPU translation of continuous batching: vLLM grows
  and shrinks a ragged batch; a TPU engine keeps the batch rectangular
  and masks.
- **Prefill/decode split**: prompts are prefilled in fixed-size chunks of
  ``prefill_len`` tokens (one compile — every chunk is the same padded
  shape) into the slot's cache stripe, so prompts up to the cache length
  are admitted without a third program; decoding advances all live slots
  together, one token per step per slot.
- **Per-slot offsets**: the model's cache mask admits position ``s`` for
  slot ``b`` iff ``s <= lengths[b] + t``, so slots at different depths
  coexist in one rectangular batch (``models/lm.py: apply_with_cache``).
- Sampling is greedy or temperature softmax via ``jax.random`` — on-device,
  no host round-trip per token beyond the sampled ids.
- **Block decode**: :meth:`decode_block` runs N decode steps as ONE
  compiled ``lax.scan`` — the sampled token feeds straight back into the
  next step on-device, and the host sees one (N, B) token block per
  dispatch instead of one round-trip per token. Off a tunnel this hides
  dispatch latency; on any topology it keeps the decode loop out of
  Python.
- **Tensor parallelism**: pass ``mesh=`` (any mesh with a ``"model"``
  axis) and the weights + KV cache shard over it — heads/ff-hidden/vocab
  split across the granted slice's chips, XLA inserting the ICI
  collectives. Prefill and decode stay the same two compiled programs.
  This is how a multi-chip grant (e.g. the BASELINE 2x2 v5e slice for a
  7B-class model that cannot fit one chip) is consumed.
- **Radix prefix caching**: a radix tree over token sequences
  (:mod:`instaslice_tpu.serving.kvcache`) caches every completed
  prompt's KV at granule boundaries; any later prompt sharing a prefix
  writes the cached stripes back (a few on-device writes) instead of
  re-running that prefill — vLLM/SGLang-style AUTOMATIC prefix
  caching, static-shape: node boundaries sit on prefill-chunk
  granules, so the remainder reuses the one compiled prefill program
  and stripe reads/writes stay one program per length. Cached nodes hold
  refcounted pool blocks, LRU-evicted under block pressure;
  :meth:`register_prefix` survives as a thin wrapper that pre-inserts
  a pinned, eviction-exempt path (deprecated — see docs/SERVING.md).
- **Parallel sampling**: :meth:`add_request_n` admits n samples of one
  prompt with ONE prefill — the KV stripe forks to the other slots
  (HBM copies), and independent per-row Gumbel noise diverges them at
  temperature > 0.
- **Stop sequences + logprobs**: host-side incremental stop scanning
  (the compiled programs never change) and per-token logprobs computed
  inside the decode scan, both carried 1:1 through every truncation
  path.
- **Multi-host**: on a multi-process mesh the engine forces replicated
  token outputs and is driven by the op-stream broadcast
  (:mod:`instaslice_tpu.serving.distributed`) so every process issues
  identical compiled calls.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from instaslice_tpu.models.lm import Params, TpuLM, param_specs
from instaslice_tpu.serving.kvcache import (
    SESSION_WIRE_VERSION,
    BlockTable,
    KVBlockPool,
    RadixIndex,
    RadixMatch,
    RadixNode,
    array_to_wire,
    radix_granule,
    tree_to_wire,
    wire_to_array,
    wire_to_tree,
)
from instaslice_tpu.serving.sampling import (
    apply_repetition_penalty,
    filter_logits,
    speculative_accept,
    token_logprob,
)
from instaslice_tpu.obs.profiler import get_profiler
from instaslice_tpu.utils.trace import get_tracer

log = logging.getLogger("instaslice_tpu.serving.engine")

#: sentinel for "no precomputed radix match passed" (None is a valid
#: match result, so it cannot be the default)
_MATCH_UNSET = object()


@dataclasses.dataclass
class GenerationResult:
    request_id: int
    prompt: List[int]
    tokens: List[int]                 # generated ids (no prompt)
    finished_reason: str = ""         # "eos" | "max_len" | ""
    # log-probability of each generated token under the distribution it
    # was sampled from (post temperature/top-k/top-p), 1:1 with tokens
    logprobs: List[float] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class AdmissionRequest:
    """One request of a burst admission (:meth:`ServingEngine.
    add_requests`): the same fields ``add_request_n`` takes, as data so
    a burst can ride one dispatch chain (and one op-stream broadcast)."""

    prompt: List[int]
    n: int = 1
    stop: Optional[list] = None
    adapter: int = 0


@dataclasses.dataclass
class _Slot:
    request_id: int
    prompt: List[int]
    generated: List[int]
    # per-request stop token sequences (host-side check: the device
    # never needs to know — wasted block-tail tokens are masked stripes)
    stop: List[List[int]] = dataclasses.field(default_factory=list)
    # positions before this are already stop-scanned (no match found);
    # rescans resume a stop-window before it, not from zero
    stop_scanned: int = 0
    # 1:1 with ``generated``; every cut to generated cuts this too
    logprobs: List[float] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Parked:
    """A preempted request: its host state plus its KV stripe(s), read
    out of the cache so the slot could go back to the batch. The block
    table stays allocated (``ServingEngine._tables``) — resume is one
    stripe write, never a re-prefill."""
    req: "_Slot"
    stripe: Params
    draft_stripe: Optional[Params]
    length: int                        # resident cache positions
    adapter: int = 0


class ServingEngine:
    def __init__(
        self,
        model: TpuLM,
        params: Optional[Params] = None,
        *,
        max_batch: int = 8,
        max_len: int = 512,
        prefill_len: int = 64,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seed: int = 0,
        mesh: Optional[Mesh] = None,
        kv_quant: bool = False,
        draft_model: Optional[TpuLM] = None,
        draft_params: Optional[Params] = None,
        spec_k: int = 4,
        top_k: int = 0,
        top_p: float = 1.0,
        min_p: float = 0.0,
        repetition_penalty: float = 1.0,
        max_prefixes: int = 8,
        kv_block_size: int = 16,
        radix_cache: bool = True,
        radix_decoded: bool = True,
        lora_adapters=None,
        lora_alphas=None,
        lora_names=None,
        batched_prefill: bool = True,
        adapter_fastpath: bool = True,
        spec_adaptive: bool = True,
    ) -> None:
        """``kv_quant=True`` stores the KV cache as int8 with per-vector
        scales (``TpuLM.init_cache(quant=True)``): decode streams the
        whole cache every step, so this halves the dominant HBM traffic
        at high concurrency and doubles cache capacity.

        ``lora_adapters`` (a list of adapter trees from
        ``models/lora.py``) enables MULTI-LoRA serving: every request
        picks an adapter (``add_request(..., adapter=k)``, 1-based; 0 =
        the unadapted base) and all of them decode in the ONE compiled
        program — the per-row delta rides a one-hot-gathered (in, r) @
        (r, out) pair (``TpuLM.apply_with_cache``). Adapters must share
        rank and target set (one static stack); ``lora_alphas`` gives
        each its training alpha (default 16).

        ``draft_model`` (+ ``draft_params``) enables LOSSLESS
        speculative decoding (:meth:`spec_step`): the draft proposes up
        to ``spec_k`` tokens per round, the target verifies them in ONE
        forward, and the accepted prefix plus one bonus/resampled token
        is emitted — ≥1 and up to ``spec_k + 1`` tokens per target
        pass. Greedy engines emit the bit-identical plain greedy chain;
        at temperature > 0 the acceptance rule is standard rejection
        sampling, so output is distribution-identical to plain sampling
        at any temperature. ``spec_adaptive`` (default on) picks each
        round's k from a bounded power-of-two-style shape set by an
        acceptance-rate EMA, degrading toward plain decode (k=0) on
        low-acceptance traffic. Rollback is free: the per-slot offset
        cache never attends past ``lengths``, and a rejected position
        is exactly the next write position.

        ``batched_prefill`` enables :meth:`add_requests`' multi-slot
        prefill program (one ``(P, prefill_len)`` dispatch per chunk
        round instead of one dispatch chain per admission, with P drawn
        from a power-of-two bucket set so the compile cache stays
        bounded); ``adapter_fastpath`` lets decode rounds whose live
        slots all share one adapter id (including 0 = base) dispatch a
        single-adapter program variant instead of the per-row one-hot
        gather. Both default on; the bench's per-slot baseline arm and
        A/B debugging turn them off."""
        if prefill_len > max_len:
            raise ValueError("prefill_len must be <= max_len")
        self.model = model
        self.params = (
            params if params is not None else model.init(jax.random.key(0))
        )
        self.max_batch = max_batch
        self.max_len = max_len
        self.prefill_len = prefill_len
        self.temperature = temperature
        # sampling filters (applied only when temperature > 0); BOTH are
        # compile-keyed statics in the block-decode path (top_k changes
        # traced shapes via lax.top_k; top_p gates a Python-level branch
        # in filter_logits), so mutating them recompiles instead of
        # silently replaying the first trace
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 <= min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {min_p}")
        if repetition_penalty <= 0.0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {repetition_penalty}"
            )
        if repetition_penalty != 1.0 and draft_model is not None:
            raise ValueError(
                "repetition_penalty cannot combine with speculative "
                "decoding: the penalty depends on tokens sampled INSIDE "
                "the verify window, which the one-shot verify forward "
                "cannot see — acceptance would silently diverge from "
                "the penalized chain"
            )
        self.top_k = top_k
        self.top_p = top_p
        self.min_p = min_p
        # construction-only (unlike temperature/top_k/top_p/min_p,
        # which may be mutated between calls): whether the seen-token
        # set exists at all is decided here, so a mutated penalty would
        # be silently ignored — the read-only property makes that loud
        self._repetition_penalty = repetition_penalty
        # seen-token presence per slot, (B, V) bool on device — only
        # materialized (and only updated) when the penalty is active
        self.track_seen = repetition_penalty != 1.0
        self.seen = (
            jnp.zeros((max_batch, model.cfg.vocab_size), jnp.bool_)
            if self.track_seen else None
        )
        self.eos_id = eos_id
        self.mesh = mesh
        # pallas w8a16 decode kernel: single-device programs only —
        # pallas_call does not auto-partition, so a tensor-parallel
        # engine must leave quantized matmuls on the einsum path XLA
        # can shard (quant.qdot's kernel_ok)
        self._quant_kernel = mesh is None or mesh.size == 1
        self._rng = jax.random.key(seed)
        self._next_id = 0
        self.kv_quant = kv_quant
        self.lora = None
        self.n_adapters = 0
        if lora_adapters:
            if draft_model is not None:
                raise ValueError(
                    "multi-LoRA cannot combine with speculative "
                    "decoding: the draft proposes from the UNADAPTED "
                    "base, so acceptance would collapse for adapted "
                    "rows — serve adapters and spec-decode separately"
                )
            from instaslice_tpu.models.lora import stack_adapters

            self.lora = stack_adapters(lora_adapters, model.cfg,
                                       alphas=lora_alphas)
            self.n_adapters = len(lora_adapters)
            if lora_names is not None and (
                len(lora_names) != self.n_adapters
                or len(set(lora_names)) != self.n_adapters
            ):
                raise ValueError(
                    "lora_names must be unique and match "
                    "lora_adapters 1:1"
                )
        #: request-facing name → 1-based engine adapter id (the mapping
        #: is engine state: it must stay consistent with the stacking
        #: order, so it lives here, not in whoever built the engine)
        self.adapter_names = (
            {n: i + 1 for i, n in enumerate(lora_names)}
            if lora_names else {}
        )
        #: per-slot adapter id (0 = base); read by every decode/prefill
        self.slot_adapter = jnp.zeros(max_batch, jnp.int32)
        self.cache = model.init_cache(max_batch, max_len, quant=kv_quant)
        self.lengths = jnp.zeros(max_batch, jnp.int32)
        self.last_token = jnp.zeros(max_batch, jnp.int32)
        if mesh is not None:
            self._shard_over(mesh)
        self.slots: Dict[int, _Slot] = {}          # slot index → request
        self.finished: List[GenerationResult] = []
        self.tokens_generated = 0
        # paged KV accounting (serving/kvcache.py): a block pool over
        # the cache's (max_batch x max_len) position space. Block
        # tables per request replace per-slot max_len reservations —
        # admission/eviction/preemption reason in blocks, and
        # kv_utilization reports true block occupancy.
        if not 1 <= kv_block_size <= max_len:
            raise ValueError(
                f"kv_block_size must be in [1, max_len], got "
                f"{kv_block_size}"
            )
        self.kv_block_size = kv_block_size
        # per-row capacity is ceil(max_len / block_size) blocks (the
        # tail partial block is real, writable positions) — floor
        # division would undersize the pool whenever max_len is not a
        # block multiple and let LIVE slots exhaust it mid-decode
        self.kv = KVBlockPool(
            max_batch * (-(-max_len // kv_block_size)),
            kv_block_size,
        )
        #: request id → block table (live slots AND parked requests)
        self._tables: Dict[int, BlockTable] = {}
        #: preempted requests parked off-batch (request id → state)
        self.parked: Dict[int, _Parked] = {}
        #: host mirror of slot_adapter (preemption must not sync)
        self._slot_adapter_host: Dict[int, int] = {}
        self.preempted_total = 0
        self.resumed_total = 0
        # live-migration ledger (docs/SERVING.md "Fleet router &
        # session migration"): parked sessions serialized off this
        # engine / deserialized onto it
        self.exported_total = 0
        self.imported_total = 0
        # ---- radix prefix cache (docs/SERVING.md "Radix prefix
        # cache") ----
        # A radix tree over token sequences replaces the PR-9-era
        # exact-match registered-prefix dict: every admitted prompt
        # walks the tree and reuses the longest cached prefix (the
        # node path's block tables fork copy-on-write at zero pool
        # cost, the per-granule KV stripes write back into the slot);
        # every completion INSERTS its prompt (and, with
        # ``radix_decoded``, its decoded tokens) so the cache learns
        # the workload with no registration step. Organic nodes hold
        # ordinary pool blocks and are LRU-evicted under block
        # pressure (leaf-first, never a node a live/parked table has
        # locked); ``register_prefix`` survives as a thin wrapper that
        # pre-inserts a PINNED, eviction-exempt path.
        self.radix_granule = radix_granule(prefill_len, kv_block_size)
        self.radix = RadixIndex(self.kv, self.radix_granule)
        self.radix_cache = radix_cache
        self.radix_decoded = radix_decoded
        #: registered prefix key → its (registered, pinned) tree node;
        #: the count is capped like the pre-radix stripe cache
        self.prefixes: Dict[tuple, RadixNode] = {}
        self.max_prefixes = max_prefixes
        #: rid → (deepest tree node its table forked, matched tokens):
        #: lock bookkeeping plus the shared-position count the
        #: utilization gauge must not double-count
        self._radix_locks: Dict[int, tuple] = {}
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_inserted = 0
        self.prefix_tokens_saved = 0
        #: fault-injection seam (instaslice_tpu.faults.engine_fault_hook):
        #: called with the op name ("prefill"/"decode"/"spec") before
        #: every device dispatch; the hook may delay, raise, or consume
        #: the donated cache exactly like a real failed jitted call —
        #: None (the default) costs one attribute read per dispatch
        self.fault_hook = None

        # ---- engine hot path (docs/SERVING.md "Engine hot path") ----
        #: multi-slot prefill: admission bursts share one dispatch
        #: chain, P rows per chunk round bucketed to powers of two
        self.batched_prefill = batched_prefill
        #: power-of-two row buckets for the batched prefill program
        #: (one compile each; a burst wider than the largest bucket
        #: splits across dispatches). Bucket 1 is deliberately ABSENT:
        #: a single-row chunk (a lone admission, or a burst whose chunk
        #: rounds drained unevenly) rides the plain per-slot prefill
        #: program — same shape family, already compiled, no extra
        #: cache entry
        self._prefill_buckets = [
            1 << i for i in range(1, max_batch.bit_length())
            if (1 << i) <= max_batch
        ]
        #: single-adapter decode fast path: skip the per-row one-hot
        #: LoRA gather when every live slot shares one adapter id
        self.adapter_fastpath = adapter_fastpath
        #: memoized (1,) device ids for the fast path (bounded by
        #: n_adapters + 1; avoids a host->device transfer per round)
        self._single_aidx_cache: Dict[int, jax.Array] = {}
        # hot-path observability (drained into ServingMetrics by the
        # scheduler; also surfaced raw on /v1/stats)
        self.prefill_batches = 0       # batched chunk dispatches
        self.prefill_rows = 0          # real rows across them
        self.prefill_pad_rows = 0      # bucket-padding rows across them
        self.fastpath_rounds = 0       # decode rounds on the single-
        self.gathered_rounds = 0       # adapter variant vs the gather
        #: per-dispatch batched-prefill occupancy samples (real rows /
        #: bucket rows), drained by Scheduler._drain_prefill_occupancy
        self._prefill_occ: List[float] = []
        #: an in-flight decode block (dispatched, tokens not yet read
        #: back) — the host/device overlap seam (decode_block_start /
        #: decode_block_finish); every other mutating entry point
        #: drains it first so engine state can never be touched with a
        #: block half-landed
        self._pending_block: Optional[dict] = None
        #: time.monotonic() stamp of the most recent dispatch's
        #: device_get landing (decode_block_finish / spec_step_finish /
        #: step).  The scheduler anchors its dispatch-gap accounting
        #: here instead of "after finish() returned" so host
        #: bookkeeping inside finish (chain stitching, EMA ladder,
        #: _sync_tables) is charged to the host, not the device.
        self.last_dispatch_landed: Optional[float] = None

        self.draft_model = draft_model
        self.spec_k = spec_k
        #: adaptive k: per-round proposal depth chosen from the bounded
        #: power-of-two-style shape set below by an acceptance-rate EMA
        #: (docs/SERVING.md "Speculative decoding"); False pins every
        #: round at ``spec_k`` (the pre-adaptive behavior)
        self.spec_adaptive = spec_adaptive
        if draft_model is not None:
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self.draft_params = (
                draft_params if draft_params is not None
                else draft_model.init(jax.random.key(1))
            )
            self.draft_cache = draft_model.init_cache(max_batch, max_len)
            if mesh is not None:
                self.draft_params, self.draft_cache = (
                    self._shard_model_state(
                        mesh, draft_model, self.draft_params,
                        self.draft_cache,
                    )
                )
        # ---- speculative decoding (docs/SERVING.md "Speculative
        # decoding") ----
        #: the bounded k shape set: 0 (a plain, draft-cache-maintaining
        #: step — the graceful-degradation floor), the powers of two
        #: below spec_k, and spec_k itself. Every dispatched k is a
        #: member, so the compiled draft/verify set stays
        #: O(log spec_k) however k adapts or shrinks near the cache end
        kset = {0}
        if draft_model is not None:
            b = 1
            while b < spec_k:
                kset.add(b)
                b <<= 1
            kset.add(spec_k)
        self._spec_kset = sorted(kset)
        #: ladder position into ``_spec_kset`` — starts optimistic (at
        #: spec_k); the acceptance EMA walks it up/down per round
        self._spec_idx = len(self._spec_kset) - 1
        #: acceptance-rate EMA (accepted draft tokens / proposed);
        #: optimistic start so the first rounds propose at full depth
        self.spec_accept_ema = 1.0
        #: consecutive k=0 rounds (drives the periodic k=1 probe that
        #: lets a recovered workload climb back out of plain decode)
        self._spec_zero_rounds = 0
        # spec observability (drained into ServingMetrics by the
        # scheduler; surfaced raw on /v1/stats "spec")
        self.spec_rounds = 0
        self.spec_proposed = 0         # draft tokens proposed (k*batch)
        self.spec_accepted = 0         # draft tokens accepted
        #: per-round acceptance-rate samples, drained by the scheduler
        #: into the tpuslice_serve_spec_acceptance_rate histogram
        self._spec_rate_samples: List[float] = []
        #: an in-flight spec round (dispatched, outputs not yet read
        #: back) — the host/device overlap seam for spec rounds
        #: (spec_step_start / spec_step_finish), drained by
        #: _drain_pending exactly like _pending_block
        self._pending_spec: Optional[dict] = None

        # multi-process (multi-host) mesh: every process executes the
        # same jitted calls (the driver/follower op-stream,
        # serving/distributed.py); host-side readbacks then need the
        # token/logit outputs REPLICATED (a process can only fetch a
        # global array it fully addresses), and host-created inputs
        # must be placed as global replicated arrays, not process-local
        self._multiproc = mesh is not None and len(
            {d.process_index for d in mesh.devices.flat}
        ) > 1
        self._replicated = (
            NamedSharding(mesh, P()) if mesh is not None else None
        )

        def rep(tree_of_outputs_spec):
            # out_shardings pytree: replicate selected outputs, leave
            # the rest (None) to sharding propagation
            return tree_of_outputs_spec if self._multiproc else None

        # every cache-transforming jit DONATES its cache argument: the
        # callers all rebind (self.cache = ...), so XLA may alias the
        # update in place instead of copying the full (L, B, H, S, hd)
        # buffer per call — without this, admission paths (prefix-cache
        # hits, parallel-sample forks) pay O(full cache) HBM per written
        # slot where a stripe write suffices. _read_stripe stays
        # donation-free: it extracts a copy while the cache lives on.
        self._prefill = jax.jit(
            self._prefill_impl,
            donate_argnums=(1,),
            out_shardings=rep((None, self._replicated)),
        )
        # multi-slot prefill: P chunks into P distinct slots' stripes
        # in ONE dispatch (P = a power-of-two bucket; one compile per
        # bucket). Logits replicate like _prefill's — admission samples
        # from them host-side.
        self._prefill_batch = jax.jit(
            self._prefill_batch_impl,
            donate_argnums=(1,),
            out_shardings=rep((None, self._replicated)),
        )
        # stripe length is a static shape: one compile per distinct
        # registered-prefix length (chunk multiples keep the set small)
        self._read_stripe = jax.jit(
            self._read_stripe_impl, static_argnames=("length",)
        )
        self._write_stripe = jax.jit(
            self._write_stripe_impl, donate_argnums=(0,)
        )
        # ``single`` (static) keys the single-adapter fast-path variant
        # of each decode program — selected host-side per round, so the
        # compiled set stays fixed: gathered + (with adapters) single
        self._decode = jax.jit(
            self._decode_impl,
            static_argnames=("single",),
            donate_argnums=(1,),
            out_shardings=rep((None, self._replicated)),
        )
        self._decode_block = jax.jit(
            self._decode_block_impl,
            static_argnames=("n_steps", "greedy", "attend_len",
                             "top_k", "top_p", "min_p", "penalize",
                             "single"),
            donate_argnums=(1,),
            out_shardings=rep(
                (None, self._replicated, self._replicated,
                 self._replicated, self._replicated, self._replicated)
            ),
        )
        if draft_model is not None:
            self._draft_prefill = jax.jit(
                self._draft_prefill_impl, donate_argnums=(1,)
            )
            self._draft_catchup = jax.jit(
                self._draft_catchup_impl, donate_argnums=(1,)
            )
            self._spec_draft = jax.jit(
                self._spec_draft_impl,
                static_argnames=("k", "greedy", "top_k", "top_p",
                                 "min_p"),
                donate_argnums=(1,),
                out_shardings=rep(
                    (None, self._replicated, self._replicated)
                ),
            )
            self._spec_verify = jax.jit(
                self._spec_verify_impl,
                static_argnames=("greedy", "top_k", "top_p", "min_p"),
                donate_argnums=(1,),
                out_shardings=rep(
                    (None, self._replicated, self._replicated,
                     self._replicated, self._replicated)
                ),
            )

    @property
    def repetition_penalty(self) -> float:
        """Construction-only (see __init__); assignment raises instead
        of being silently ignored."""
        return self._repetition_penalty

    def _shard_model_state(self, mesh: Mesh, model: TpuLM, params, cache):
        """One model's tensor-parallel layout over the mesh's ``model``
        axis: weights per :func:`param_specs` (heads / ff-hidden / vocab
        split, quant-aware), KV cache over the heads axis. Shared by the
        target and the speculative draft so the two layouts cannot
        drift."""
        if "model" not in mesh.axis_names:
            raise ValueError(
                f"serving mesh needs a 'model' axis, got {mesh.axis_names}"
            )
        tp = mesh.shape["model"]
        if model.cfg.n_heads % tp:
            raise ValueError(
                f"n_heads={model.cfg.n_heads} not divisible by the "
                f"mesh's model axis ({tp} devices)"
            )
        if model.cfg.kv_heads % tp:
            raise ValueError(
                f"kv_heads={model.cfg.kv_heads} not divisible by the "
                f"mesh's model axis ({tp} devices) — the KV cache "
                "shards over heads"
            )
        from instaslice_tpu.models.quant import shard_params

        params = shard_params(params, mesh, param_specs(model.cfg))
        # head-major cache: heads (the TP-sharded axis) sit at axis 2
        cache_sharding = NamedSharding(mesh, P(None, None, "model"))
        cache = jax.tree.map(
            lambda c: jax.device_put(c, cache_sharding), cache
        )
        return params, cache

    def _shard_over(self, mesh: Mesh) -> None:
        """Tensor-parallel layout for the target model + replicated
        decode state. XLA's sharding propagation inserts the collectives
        — the same two compiled programs serve any slice size."""
        self.params, self.cache = self._shard_model_state(
            mesh, self.model, self.params, self.cache
        )
        replicated = NamedSharding(mesh, P())
        self.lengths = jax.device_put(self.lengths, replicated)
        self.last_token = jax.device_put(self.last_token, replicated)
        self.slot_adapter = jax.device_put(self.slot_adapter, replicated)
        if self.lora is not None:
            # adapter stacks replicate: at rank ≤ 64 they are a few MB
            # per target (vs the GB-scale tp-sharded base), and the
            # per-row gather contracts the whole (in, r)/(r, out) pair
            # anyway — sharding them would trade a broadcast for
            # collectives inside every decode step
            self.lora = jax.device_put(self.lora, replicated)
        if getattr(self, "track_seen", False):
            self.seen = jax.device_put(self.seen, replicated)

    # ------------------------------------------------------------- jitted

    def _prefill_stripe(self, model, params, cache, tokens, slot, offset,
                        aidx=None):
        """Prefill one (1, prefill_len) chunk into a slot's cache stripe
        at ``offset``; returns (cache, chunk logits (prefill_len, vocab)).
        Shared by the target and draft prefills.

        The stripe is read back (not zeroed): chunks after the first must
        attend to the KV the earlier chunks wrote. Stale data from a prior
        occupant of the slot is harmless — positions [offset, offset+T)
        are overwritten before attention and the cache mask admits nothing
        beyond ``offset + t``."""
        stripe = jax.tree.map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1),
            cache,
        )
        use_lora = self.lora is not None and model is self.model
        logits, stripe = model.apply_with_cache(
            params, tokens, stripe,
            jnp.full((1,), offset, jnp.int32),
            lora=self.lora if use_lora else None,
            adapter_idx=aidx if use_lora else None,
            quant_kernel=self._quant_kernel,
        )
        cache = jax.tree.map(
            lambda c, s: jax.lax.dynamic_update_slice_in_dim(
                c, s, slot, axis=1
            ),
            cache, stripe,
        )
        return cache, logits[0]

    def _prefill_impl(self, params, cache, tokens, slot, offset, aidx):
        return self._prefill_stripe(
            self.model, params, cache, tokens, slot, offset, aidx=aidx
        )

    def _prefill_batch_impl(self, params, cache, tokens, slots, offsets,
                            aidx):
        """Prefill P same-shaped chunks into P slots' cache stripes in
        ONE dispatch: gather the P stripes, run the model once over the
        (P, prefill_len) batch (each row masked to its own offset), and
        scatter the stripes back. Rows are independent — per-row
        results are exactly what P separate ``_prefill`` calls produce.
        Padding rows (bucket alignment) duplicate a real row: the
        scatter then writes identical values twice, which is idempotent
        whatever order XLA picks. Returns (cache, (P, prefill_len,
        vocab) logits)."""
        stripes = jax.tree.map(lambda c: jnp.take(c, slots, axis=1),
                               cache)
        use_lora = self.lora is not None
        logits, stripes = self.model.apply_with_cache(
            params, tokens, stripes, offsets,
            lora=self.lora if use_lora else None,
            adapter_idx=aidx if use_lora else None,
            quant_kernel=self._quant_kernel,
        )
        cache = jax.tree.map(
            lambda c, s: c.at[:, slots].set(s), cache, stripes,
        )
        return cache, logits

    def _read_stripe_impl(self, cache, slot, start, *, length: int):
        """Copy out one slot's cache positions [start, start+length) —
        every leaf is (L, B, H, S[, hd]) with slot on axis 1 and
        position on axis 3 (head-major — see ``TpuLM.init_cache``).
        ``start`` is TRACED (radix granules read at arbitrary chunk
        offsets without growing the compiled set); ``length`` stays the
        compile-keyed static."""

        def rd(c):
            one = jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1)
            return jax.lax.dynamic_slice_in_dim(one, start, length,
                                                axis=3)

        return jax.tree.map(rd, cache)

    def _write_stripe_impl(self, cache, stripe, slot, start):
        """Write a stored stripe into a slot at position ``start``
        (TRACED — radix path segments land at their own offsets through
        the one compiled program per stripe length). Stripes are
        absolute-position entities either way: RoPE bakes positions
        into K, so a segment only ever writes back at the offset it was
        read from."""

        def wr(c, s):
            starts = (jnp.int32(0), slot, jnp.int32(0), start) + \
                (jnp.int32(0),) * (c.ndim - 4)
            return jax.lax.dynamic_update_slice(c, s, starts)

        return jax.tree.map(wr, cache, stripe)

    def _decode_impl(self, params, cache, last_token, lengths, aidx, *,
                     single: bool = False):
        logits, cache = self.model.apply_with_cache(
            params, last_token[:, None], cache, lengths,
            lora=self.lora,
            adapter_idx=aidx if self.lora is not None else None,
            quant_kernel=self._quant_kernel,
            single_adapter=single,
        )
        return cache, logits[:, 0]                  # (B, vocab)

    def _decode_block_impl(self, params, cache, last_token, lengths, rng,
                           temperature, seen, penalty, aidx, *,
                           n_steps: int,
                           greedy: bool, attend_len: int = 0,
                           top_k: int = 0, top_p: float = 1.0,
                           min_p: float = 0.0, penalize: bool = False,
                           single: bool = False):
        """``n_steps`` decode steps as one ``lax.scan``: each sampled
        token feeds the next step on-device — no host round-trip inside
        the block. Returns the advanced state plus the (n_steps, B) token
        block.

        ``greedy`` is a static (compile-keyed) switch while
        ``temperature`` stays a traced value, so mutating
        ``self.temperature`` between calls behaves like :meth:`step`
        instead of silently replaying the first trace. ``penalize``
        (static) threads the per-slot seen-token set through the scan —
        the repetition penalty must observe tokens sampled EARLIER IN
        THIS BLOCK, not just pre-block state; when off, ``seen`` passes
        through untouched and XLA eliminates it."""

        def step(carry, i):
            cache, last, lens, seen = carry
            logits, cache = self.model.apply_with_cache(
                params, last[:, None], cache, lens,
                attend_len=attend_len,
                lora=self.lora,
                adapter_idx=aidx if self.lora is not None else None,
                quant_kernel=self._quant_kernel,
                single_adapter=single,
            )
            logits = logits[:, 0]
            if penalize:
                # BEFORE temperature/filters: the HF order
                logits = apply_repetition_penalty(logits, seen, penalty)
            if greedy:
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                # temperature FIRST, then the nucleus: the top_p set is
                # defined over the tempered distribution (the standard
                # order OpenAI/HF clients are calibrated against)
                logits = filter_logits(
                    logits / temperature, top_k, top_p, min_p
                )
                toks = jax.random.categorical(
                    jax.random.fold_in(rng, i), logits, axis=-1,
                ).astype(jnp.int32)
            if penalize:
                seen = seen.at[
                    jnp.arange(seen.shape[0]), toks
                ].set(True)
            # logprob under the distribution actually sampled from
            lp = token_logprob(logits, toks)
            return (cache, toks, lens + 1, seen), (toks, lp)

        (cache, last, lengths, seen), (toks, lps) = jax.lax.scan(
            step, (cache, last_token, lengths, seen),
            jnp.arange(n_steps, dtype=jnp.int32),
        )
        return cache, last, lengths, seen, toks, lps

    def _draft_prefill_impl(self, params, cache, tokens, slot, offset):
        """The draft cache must hold the prompt too before it can
        propose (logits discarded — only the target samples)."""
        cache, _ = self._prefill_stripe(
            self.draft_model, params, cache, tokens, slot, offset
        )
        return cache

    def _draft_catchup_impl(self, params, cache, inputs, lens):
        """Teacher-force ``inputs`` (B, T) through the draft so its
        cache tracks tokens produced OUTSIDE spec_step (plain step() /
        decode_block() on a draft-enabled engine) — otherwise those
        positions would be zero-holes the draft attends forever."""
        _, cache = self.draft_model.apply_with_cache(
            params, inputs, cache, lens,
            quant_kernel=self._quant_kernel,
        )
        return cache

    def _spec_draft_impl(self, params, cache, last, lens, rng,
                         temperature, *, k: int, greedy: bool,
                         top_k: int = 0, top_p: float = 1.0,
                         min_p: float = 0.0):
        """k draft steps as one scan → (B, k) proposals. Greedy
        (temperature -> 0): argmax chains, the bit-identical legacy
        path. Sampling: each step draws from the FILTERED, tempered
        draft distribution q (same temperature/top-k/top-p/min-p the
        target applies), and the per-step q distributions (B, k, V)
        ride along — rejection sampling needs them for the
        accept-or-resample math."""

        def step(carry, i):
            cache, last, lens = carry
            logits, cache = self.draft_model.apply_with_cache(
                params, last[:, None], cache, lens,
                quant_kernel=self._quant_kernel,
            )
            logits = logits[:, 0]
            if greedy:
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (cache, toks, lens + 1), toks
            logits = filter_logits(logits / temperature, top_k, top_p,
                                   min_p)
            toks = jax.random.categorical(
                jax.random.fold_in(rng, i), logits, axis=-1,
            ).astype(jnp.int32)
            return (cache, toks, lens + 1), (
                toks, jax.nn.softmax(logits, axis=-1)
            )

        (cache, _, _), out = jax.lax.scan(
            step, (cache, last, lens), jnp.arange(k, dtype=jnp.int32)
        )
        if greedy:
            # uniform output structure across the greedy/sampling
            # statics (one out_shardings spec serves both): greedy has
            # no proposal distributions, so q is a scalar placeholder
            return (cache, jnp.swapaxes(out, 0, 1),
                    jnp.zeros((1,), jnp.float32))
        toks, q = out
        return (cache, jnp.swapaxes(toks, 0, 1),
                jnp.swapaxes(q, 0, 1))

    def _spec_verify_impl(self, params, cache, inputs, lens, d, q, rng,
                          temperature, *, greedy: bool, top_k: int,
                          top_p: float, min_p: float):
        """One target forward over (B, k+1) inputs, fused with the
        acceptance rule. Greedy: accept the longest draft prefix
        agreeing with the target's argmax chain (bit-identical to plain
        greedy decode). Sampling: standard rejection sampling
        (:func:`instaslice_tpu.serving.sampling.speculative_accept`) —
        output distribution-identical to plain sampling from the
        filtered, tempered target distribution at ANY temperature.

        Returns ``(cache, accepted (B,), out (B, k+1), logprobs
        (B, k+1), final (B,))``: ``out[:, :accepted]`` is the emitted
        draft prefix, ``out[:, accepted]`` the bonus/resampled token
        (``final``), positions past that are garbage the host slices
        off. ``lengths`` advance by ``accepted + 1`` — all computed
        on-device so the overlap seam never forces a readback."""
        logits, cache = self.model.apply_with_cache(
            params, inputs, cache, lens,
            quant_kernel=self._quant_kernel,
        )
        B, k1 = inputs.shape
        k = k1 - 1
        rows = jnp.arange(B)
        if greedy:
            t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            matches = (d == t[:, :k]).astype(jnp.int32)
            accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
            final = t[rows, accepted]
            out = jnp.concatenate(
                [d, jnp.zeros((B, 1), jnp.int32)], axis=1
            ).at[rows, accepted].set(final)
            # emitted tokens ARE the target's greedy chain t[:n+1]
            # (accepted draft tokens equal it), so their logprobs are
            # the verify pass's logprobs at those positions
            return cache, accepted, out, token_logprob(logits, t), final
        p = jax.nn.softmax(
            filter_logits(logits / temperature, top_k, top_p, min_p),
            axis=-1,
        )
        accepted, out, lps, final = speculative_accept(d, q, p, rng)
        return cache, accepted, out, lps, final

    def _sample(self, logits: jax.Array, rows=None):
        """(tokens, logprobs) for a (B, vocab) logits batch; logprob is
        under the distribution actually sampled from (post penalty/
        temperature/top-k/top-p/min-p filtering). ``rows`` maps logits
        rows to slot indices when the batch is a subset (admission
        forks); None means row i IS slot i (the full-batch decode)."""
        if self.track_seen:
            seen = (self.seen if rows is None
                    else self.seen[jnp.asarray(rows)])
            logits = apply_repetition_penalty(
                logits, seen, self.repetition_penalty
            )
        if self.temperature <= 0.0:
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            self._rng, sub = jax.random.split(self._rng)
            # temperature first, then the nucleus (_decode_block_impl)
            logits = filter_logits(
                logits / self.temperature, self.top_k, self.top_p,
                self.min_p,
            )
            toks = jax.random.categorical(sub, logits, axis=-1).astype(
                jnp.int32
            )
        return toks, token_logprob(logits, toks)

    def _adapter_args(self):
        """(aidx, single) for this round's decode dispatch. When every
        live slot shares one adapter id (including 0 = base) and the
        fast path is on, dispatch the single-adapter program variant:
        ``aidx`` becomes a memoized (1,) id and the compiled program
        indexes the stacked LoRA tree once instead of one-hot-gathering
        per row. Selection is host-side (``_slot_adapter_host``), so
        the compiled-program set stays fixed: gathered + single."""
        if self.lora is None:
            return self.slot_adapter, False
        if self.adapter_fastpath:
            ids = {self._slot_adapter_host.get(s, 0) for s in self.slots}
            if len(ids) == 1:
                self.fastpath_rounds += 1
                return self._single_aidx(ids.pop()), True
        self.gathered_rounds += 1
        return self.slot_adapter, False

    def _single_aidx(self, aid: int) -> jax.Array:
        arr = self._single_aidx_cache.get(aid)
        if arr is None:
            arr = jnp.full((1,), aid, jnp.int32)
            if self._replicated is not None:
                arr = jax.device_put(arr, self._replicated)
            self._single_aidx_cache[aid] = arr
        return arr

    # -------------------------------------------------------------- public

    def free_slots(self) -> int:
        return self.max_batch - len(self.slots)

    def _resident_tokens(self) -> int:
        """Tokens holding KV blocks right now: live slots plus parked
        (preempted) requests — host-side bookkeeping, no device sync.
        list() snapshots the dict views first: /v1/stats reads this
        from HTTP handler threads while the scheduler mutates the
        dicts (a point-in-time approximation is fine for a gauge; a
        'changed size during iteration' crash is not)."""
        live = sum(
            len(r.prompt) + len(r.generated)
            for r in list(self.slots.values())
        )
        # radix-cached tokens are resident too (their nodes hold the
        # blocks in the denominator) — but positions a live/parked
        # table SHARES with its matched path must count ONCE: the
        # per-rid matched lengths subtract exactly the double-counted
        # span, so steady prefix-hit traffic reads true occupancy
        # instead of saturating the gauge at 1.0
        shared = sum(length
                     for _, length in list(self._radix_locks.values()))
        return max(0, live
                   + sum(p.length for p in list(self.parked.values()))
                   + self.radix.tokens_cached() - shared)

    def kv_utilization(self) -> float:
        """True block-pool occupancy: resident tokens / capacity of the
        blocks actually allocated for them. Stays high under mixed
        sequence lengths — a request holds only the blocks its tokens
        fill, never a ``max_len`` stripe. Feeds
        ``tpuslice_serve_kv_cache_utilization``; MIG-serving
        reconfiguration papers key decisions off exactly this occupancy
        signal. (The pre-paging stripe metric — live tokens over the
        whole max_batch × max_len rectangle — rode one release as
        ``kv_utilization_legacy`` / gauge ``..._legacy`` after PR 9 and
        is now retired.)"""
        return self.kv.utilization(self._resident_tokens())

    def kv_stats(self) -> dict:
        """Block-pool gauges (free/used/cow + parked count) for
        /v1/stats and the ``tpuslice_kv_blocks_*`` metrics. dict()
        snapshots the table map — this runs on HTTP handler threads
        concurrently with the scheduler's mutations."""
        out = self.kv.stats(dict(self._tables))
        out["parked"] = len(self.parked)
        out["utilization"] = self.kv_utilization()
        #: pool blocks the radix prefix cache holds (the
        #: tpuslice_kv_blocks_prefix gauge), and how many of those a
        #: reclaim could free right now
        out["prefix_blocks"] = self.radix.pool_blocks()
        out["prefix_evictable"] = self.radix.evictable_blocks()
        return out

    @property
    def prefix_evicted(self) -> int:
        """Radix nodes evicted since construction (LRU reclaim +
        drop_prefix cascades) — the counter behind
        ``tpuslice_serve_prefix_evicted_total``."""
        return self.radix.evictions

    def radix_stats(self) -> dict:
        """The radix prefix cache's observability block (/v1/stats
        ``radix``): structure gauges + the hit/miss/insert/evict
        ledger. Tree walks list()-snapshot child maps, so HTTP stats
        threads can read while the scheduler mutates."""
        return {
            "enabled": self.radix_cache,
            "decoded": self.radix_decoded,
            "granule": self.radix_granule,
            "nodes": self.radix.node_count(),
            "tokens": self.radix.tokens_cached(),
            "blocks": self.radix.pool_blocks(),
            "evictable_blocks": self.radix.evictable_blocks(),
            "hits": self.prefix_hits,
            "misses": self.prefix_misses,
            "inserted": self.prefix_inserted,
            "evicted": self.prefix_evicted,
            "tokens_saved": self.prefix_tokens_saved,
        }

    def compiled_programs(self) -> Dict[str, int]:
        """Per-jit compile-cache sizes — the observable behind the
        "bounded compiled-program set" claim (asserted by the
        compile-count regression test, surfaced on ``/v1/stats``).
        Every entry is the number of distinct programs XLA compiled for
        that dispatch form so far this process."""
        out: Dict[str, int] = {}
        for name in ("_prefill", "_prefill_batch", "_read_stripe",
                     "_write_stripe", "_decode", "_decode_block",
                     "_draft_prefill", "_draft_catchup", "_spec_draft",
                     "_spec_verify"):
            f = getattr(self, name, None)
            if f is None:
                continue
            try:
                out[name.lstrip("_")] = f._cache_size()
            # observability only: a jax internals change must degrade
            # to a missing entry, never break /v1/stats
            except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
                pass
        return out

    def compile_budget(self, block_cap: int = 0) -> Dict[str, int]:
        """The DOCUMENTED upper bound on compiled programs per dispatch
        form for this engine's configuration (docs/SERVING.md "Engine
        hot path") — what :meth:`compiled_programs` is asserted
        against. ``block_cap`` is the largest decode-block length the
        caller dispatches (the scheduler's ``block_size``; 0 = assume
        up to ``max_len``).

        - prefill: 1 (every chunk is the same padded shape; lone
          burst rows reuse it too — bucket 1 does not exist)
        - prefill_batch: one per power-of-two row bucket (2..max_batch)
        - decode / decode_block: gathered + (with adapters) the
          single-adapter variant, times the power-of-two step counts
          and 256-position attend buckets for the block form
        - read/write_stripe: one per distinct static stripe length —
          chunk multiples (radix granules, fork stripes) plus block
          multiples (preemption roundings). Radix stripe traffic adds
          NO programs: the position offset is traced, and the granule
          is itself a chunk multiple already in the set.
        """
        cap = block_cap or self.max_len
        # power-of-two n_steps values in [1, cap]
        n_steps = max(1, cap).bit_length()
        # attend buckets: multiples of 256 below max_len, plus the
        # full-cache (attend_len=0) variant
        attend = max(1, -(-self.max_len // 256))
        variants = 2 if self.lora is not None else 1
        chunk_lens = self.max_len // self.prefill_len
        block_lens = -(-self.max_len // self.kv_block_size)
        stripe_lens = chunk_lens + block_lens
        out = {
            "prefill": 1,
            "prefill_batch": len(self._prefill_buckets),
            "decode": variants,
            "decode_block": n_steps * attend * variants,
            "read_stripe": stripe_lens,
            "write_stripe": stripe_lens,
        }
        if self.draft_model is not None:
            # catch-up consumes (B, 1) from step() and (B, n) from
            # decode_block; every dispatched spec k is a member of the
            # bounded shape set (adaptive ladder, cache-end shrink and
            # budget caps all floor onto it), times the greedy/sampled
            # variants (temperature is mutable between calls)
            out.update({
                "draft_prefill": 1,
                "draft_catchup": 1 + n_steps,
                "spec_draft": 2 * len(self._spec_kset),
                "spec_verify": 2 * len(self._spec_kset),
            })
        return out

    def warm_prefill_buckets(self) -> None:
        """Compile every batched-prefill bucket NOW, against the live
        cache, with zero admissions — call once before taking traffic
        (the serve CLI does; the bench does per arm) so no burst pays
        a compile mid-measurement. The dummy rows write masked
        positions of slot 0's stripe: harmless while no slot is live
        (admission prefill overwrites everything it attends). No-op
        with batched prefill off."""
        if not self.batched_prefill or not self._prefill_buckets:
            return
        if self.slots:
            raise RuntimeError(
                "warm_prefill_buckets must run before any admission "
                "(it scribbles on slot 0's masked stripe)"
            )
        P = self.prefill_len
        for b in self._prefill_buckets:
            self.cache, _ = self._prefill_batch(
                self.params, self.cache,
                jnp.zeros((b, P), jnp.int32),
                jnp.zeros(b, jnp.int32),
                jnp.zeros(b, jnp.int32),
                jnp.zeros(b, jnp.int32),
            )

    def _release_table(self, rid: int) -> None:
        """THE per-rid teardown choke point: returns the block table's
        references AND the radix-path lock the admission took — live
        finishes, evictions, parked drops, and recovery all come
        through here, so a tree node can never stay pinned by a dead
        rid."""
        t = self._tables.pop(rid, None)
        if t is not None:
            self.kv.release(t)
        held = self._radix_locks.pop(rid, None)
        if held is not None:
            self.radix.unlock(held[0])

    def _sync_tables(self) -> None:
        """Grow every live slot's block table to its token count —
        called after each decode dispatch so freed/grown blocks are
        visible to the very next admission decision. INCREMENTAL: a
        slot whose growth stays inside its current blocks (no new
        block, no shared boundary to copy) just bumps the token count —
        zero allocator work — so the post-readback host window stays
        thin and scheduler planning overlaps device compute. Never
        raises for engine-only use: live tables cannot exceed the pool
        (each slot is bounded by its row); only parked state can
        over-subscribe, and the scheduler's headroom guard sheds it
        first."""
        for slot, req in self.slots.items():
            t = self._tables.get(req.request_id)
            if t is None:
                continue
            total = len(req.prompt) + len(req.generated)
            if not self.kv.bump(t, total):
                # cached-but-unreferenced radix blocks yield to live
                # growth before ensure() can see exhaustion (the
                # headroom guard counted them as free)
                self._reclaim_for(self.kv.growth_cost(t, total))
                self.kv.ensure(t, total)

    def can_admit(self, prompt, n: int = 1, adapter: int = 0,
                  match=_MATCH_UNSET) -> bool:
        """Step-level admission check: free slots AND free KV blocks.
        The scheduler gates on this each step instead of slot count
        alone, so parked blocks correctly push back on admission.

        ``prompt`` may be the token list (the scheduler's form — the
        block count then charges only the NON-SHARED suffix of a radix
        hit, via :meth:`admit_block_cost`) or a bare length (the
        conservative full-prompt charge). Either way the count mirrors
        :meth:`_alloc_tables` exactly, and cached-but-unreferenced
        radix blocks count as free (admission reclaims them
        deterministically), so any HTTP-valid request fits an empty
        pool — a False here always means "blocks will free", never
        "never"."""
        if self.free_slots() < n:
            return False
        if isinstance(prompt, int):
            need = self.kv.blocks_for(prompt + 1) + (n - 1)
        else:
            if match is _MATCH_UNSET:
                match = (self._match_prefix(prompt) if adapter == 0
                         else None)
            # the matched path's own evictable blocks leave the supply
            # the moment admission locks it (match_reserve)
            need = (self.admit_block_cost(prompt, n, adapter,
                                          match=match)
                    + self.match_reserve(match))
        return need <= (self.kv.free_blocks()
                        + self.radix.evictable_blocks())

    def finish_slot(self, slot: int, n_keep: Optional[int] = None,
                    reason: str = "max_new_tokens") -> None:
        """Externally finish a live slot (budget cut, client eviction):
        move it to ``finished`` with at most ``n_keep`` tokens.

        All EXTERNAL slot removals must go through here — slot
        occupancy feeds the compiled decode's static attend window, so
        in multi-process serving this op is part of the broadcast
        stream (:mod:`instaslice_tpu.serving.distributed`); internal
        removals (eos/stop/max_len in ``_maybe_finish``) replay
        deterministically from the op stream and need no broadcast."""
        self._drain_pending()
        req = self.slots.pop(slot)
        self._release_table(req.request_id)
        # the prompt (and its decode chain) just proved it is real
        # traffic: teach the radix cache before the slot is reused
        self._radix_insert(slot, req)
        toks = req.generated if n_keep is None else req.generated[:n_keep]
        lps = req.logprobs if n_keep is None else req.logprobs[:n_keep]
        self.finished.append(
            GenerationResult(req.request_id, req.prompt, toks, reason,
                             logprobs=lps)
        )

    def evict_slot(self, slot: int) -> None:
        """Drop a live slot with NO result (abandoned request): the
        tokens were never delivered to anyone. Its blocks are free for
        the next admission immediately."""
        self._drain_pending()
        req = self.slots.pop(slot)
        self._release_table(req.request_id)

    # ------------------------------------------------------ preempt/resume

    def preempt_slot(self, slot: int) -> int:
        """Park a live request off-batch: read its KV stripe out of the
        cache, free the slot, KEEP its block table — the cheap half of
        SLO preemption (resume is one stripe write, no re-prefill).
        Part of the multi-host broadcast surface like finish_slot (slot
        occupancy feeds the compiled decode's attend window); returns
        the parked request id."""
        self._drain_pending()
        if self.fault_hook is not None:
            self.fault_hook("prefill")
        req = self.slots[slot]
        # resident positions: generated[-1] is the pending last_token,
        # not yet written to the cache (see _step_inner)
        length = len(req.prompt) + len(req.generated) - 1
        # stripe lengths round up to block multiples: one compile per
        # distinct rounded length, bounded by max_len / kv_block_size
        rounded = min(
            self.max_len,
            self.kv.blocks_for(max(1, length)) * self.kv_block_size,
        )
        stripe = self._read_stripe(self.cache, slot, 0, length=rounded)
        draft_stripe = None
        if self.draft_model is not None:
            draft_stripe = self._read_stripe(
                self.draft_cache, slot, 0, length=rounded
            )
        del self.slots[slot]
        self.parked[req.request_id] = _Parked(
            req, stripe, draft_stripe, length,
            adapter=self._slot_adapter_host.get(slot, 0),
        )
        self.preempted_total += 1
        return req.request_id

    def resume_request(self, rid: int) -> int:
        """Un-park a preempted request into a free slot: write its
        stripe back (positions are absolute — RoPE bakes them into K,
        so the stripe is row-position-exact), restore decode state, and
        return the slot. Raises when no slot is free or the rid is not
        parked (callers check, like add_request's capacity)."""
        self._drain_pending()
        if rid not in self.parked:
            raise ValueError(f"request {rid} is not parked")
        slot = self._first_free_slot("no free slot to resume into")
        if self.fault_hook is not None:
            self.fault_hook("prefill")
        # the entry stays parked until the device writes land: a
        # failed stripe write must leave the rid findable by
        # drop_parked (the scheduler's cleanup path), or its block
        # table would leak out of the pool forever
        parked = self.parked[rid]
        req = parked.req
        self.cache = self._write_stripe(self.cache, parked.stripe, slot,
                                        0)
        if self.draft_model is not None and parked.draft_stripe is not None:
            self.draft_cache = self._write_stripe(
                self.draft_cache, parked.draft_stripe, slot, 0
            )
        del self.parked[rid]
        self.lengths = self.lengths.at[slot].set(parked.length)
        self.last_token = self.last_token.at[slot].set(
            req.generated[-1]
        )
        if self.lora is not None:
            self.slot_adapter = self.slot_adapter.at[slot].set(
                parked.adapter
            )
        self._slot_adapter_host[slot] = parked.adapter
        if self.track_seen:
            seen_toks = jnp.asarray(
                list(req.prompt) + list(req.generated), jnp.int32
            )
            self.seen = self.seen.at[slot].set(False)
            self.seen = self.seen.at[slot, seen_toks].set(True)
        self.slots[slot] = req
        self.resumed_total += 1
        return slot

    def drop_parked(self, rid: int) -> bool:
        """Shed a parked request entirely (KV-pressure eviction or a
        client that 503'd while parked): its blocks return to the pool
        NOW — eviction frees blocks, not stripes."""
        parked = self.parked.pop(rid, None)
        if parked is None:
            return False
        self._release_table(rid)
        return True

    # ------------------------------------------------- session migration

    def model_signature(self) -> dict:
        """What two engines must agree on for a KV session to move
        between them (docs/SERVING.md "Fleet router & session
        migration") — checked at :meth:`import_session` so a blob from
        a differently-shaped replica is REJECTED instead of silently
        resuming garbage attention state."""
        cfg = self.model.cfg
        return {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "kv_heads": cfg.kv_heads,
            "d_ff": cfg.d_ff, "vocab_size": cfg.vocab_size,
            "window": cfg.window,
            "max_len": self.max_len, "prefill_len": self.prefill_len,
            "kv_block_size": self.kv_block_size,
            "kv_quant": bool(self.kv_quant),
            "n_adapters": self.n_adapters,
            "draft": self.draft_model is not None,
        }

    def _sampling_signature(self) -> dict:
        """Sampling config is engine-level; a migrated continuation
        must sample from the same distribution it started under."""
        return {
            "temperature": float(self.temperature),
            "top_k": int(self.top_k), "top_p": float(self.top_p),
            "min_p": float(self.min_p),
            "repetition_penalty": float(self.repetition_penalty),
        }

    def export_session(self, rid: int) -> dict:
        """Serialize a PARKED request into the versioned session wire
        format (``SESSION_WIRE_VERSION``, serving/kvcache.py): the
        block-rounded KV stripe :meth:`preempt_slot` read out (plus the
        draft stripe, host decode state, adapter id, and the engine RNG
        key) as a JSON-safe dict a peer replica's
        :meth:`import_session` can feed to :meth:`resume_request` with
        ZERO re-prefill.

        Pure read: the rid STAYS parked here — the migration's safety
        rule is copy-then-delete, so the caller drops the source copy
        (:meth:`drop_parked`, broadcast surface) only after the blob is
        safely on the wire. Callers preempt live slots first
        (``preempt_slot`` is the broadcast-surface half that changes
        slot occupancy).

        The RNG key rides the blob so a sampled (temperature > 0)
        continuation resumed on an RNG-fresh destination replays the
        source's exact stream — import ADOPTS it, which is
        distribution-preserving for any co-resident sessions (one
        uniformly-random key replaces another) and deterministic across
        op-stream followers."""
        if self._multiproc:
            raise RuntimeError(
                "session export over a multi-process mesh is not "
                "supported: the KV stripe is sharded across processes "
                "and no single host fully addresses it (migrate "
                "between slices, not out of one)"
            )
        parked = self.parked.get(rid)
        if parked is None:
            raise ValueError(
                f"request {rid} is not parked (export serializes "
                "parked state; preempt_slot the live slot first)"
            )
        req = parked.req
        blob = {
            "version": SESSION_WIRE_VERSION,
            "model": self.model_signature(),
            "sampling": self._sampling_signature(),
            "prompt": [int(t) for t in req.prompt],
            "generated": [int(t) for t in req.generated],
            "logprobs": [float(x) for x in req.logprobs],
            "stop": [[int(x) for x in s] for s in req.stop],
            "stop_scanned": int(req.stop_scanned),
            "length": int(parked.length),
            "adapter": int(parked.adapter),
            "stripe": tree_to_wire(jax.device_get(parked.stripe)),
            "draft_stripe": (
                tree_to_wire(jax.device_get(parked.draft_stripe))
                if parked.draft_stripe is not None else None
            ),
            "rng": array_to_wire(
                jax.device_get(jax.random.key_data(self._rng))
            ),
        }
        self.exported_total += 1
        return blob

    def _validate_session_blob(self, blob) -> None:
        """Reject a blob this engine cannot resume — wire version,
        model/sampling signature, adapter range. Split out so the
        multi-host driver can pre-screen BEFORE broadcasting (a
        rejected blob must never enter the op stream)."""
        ver = blob.get("version") if isinstance(blob, dict) else None
        if ver != SESSION_WIRE_VERSION:
            raise ValueError(
                f"unsupported session wire version {ver!r} (this "
                f"engine speaks v{SESSION_WIRE_VERSION}; re-export "
                "from a matching release)"
            )
        sig = self.model_signature()
        if blob.get("model") != sig:
            raise ValueError(
                "session blob was exported by an incompatible engine: "
                f"theirs {blob.get('model')!r} vs ours {sig!r}"
            )
        if blob.get("sampling") != self._sampling_signature():
            raise ValueError(
                "session blob sampling config mismatch: resuming "
                f"{blob.get('sampling')!r} under "
                f"{self._sampling_signature()!r} would silently change "
                "the output distribution"
            )
        if not 0 <= int(blob.get("adapter", 0)) <= self.n_adapters:
            raise ValueError(
                f"session blob adapter {blob.get('adapter')} out of "
                f"range (engine has {self.n_adapters})"
            )

    def import_session(self, blob: dict) -> int:
        """Deserialize an exported session into a PARKED request on
        this engine: allocate its block table, re-materialize the KV
        stripe(s) on device, and register the parked state so
        :meth:`resume_request` continues the decode with zero
        re-prefill. Returns the fresh LOCAL request id (rids are
        per-engine; the wire format deliberately carries none).

        Raises ``ValueError`` on wire-version / model-signature /
        sampling mismatch (the blob is untouched state from another
        process — reject, never guess) and ``RuntimeError`` when the
        pool cannot hold the stripe even after reclaiming evictable
        radix cache."""
        self._drain_pending()
        self._validate_session_blob(blob)
        length = int(blob["length"])
        if not 0 < length < self.max_len:
            raise ValueError(
                f"session length {length} outside (0, {self.max_len})"
            )
        need = length + 1
        # cached-but-unreferenced radix blocks yield to an inbound
        # session exactly like they yield to admission
        self._reclaim_for(self.kv.blocks_for(need))
        try:
            table = self.kv.allocate(need)
        except Exception as e:
            raise RuntimeError(
                f"kv block pool cannot hold the inbound session: {e}"
            ) from None
        try:
            stripe = jax.tree.map(jnp.asarray,
                                  wire_to_tree(blob["stripe"]))
            draft_stripe = None
            if blob.get("draft_stripe") is not None:
                draft_stripe = jax.tree.map(
                    jnp.asarray, wire_to_tree(blob["draft_stripe"])
                )
            if self._replicated is not None:
                stripe = jax.device_put(stripe, self._replicated)
                if draft_stripe is not None:
                    draft_stripe = jax.device_put(draft_stripe,
                                                  self._replicated)
            req = _Slot(
                0,  # rid assigned below, after nothing can fail
                [int(t) for t in blob["prompt"]],
                [int(t) for t in blob["generated"]],
                stop=[[int(x) for x in s] for s in blob["stop"]],
                stop_scanned=int(blob["stop_scanned"]),
                logprobs=[float(x) for x in blob["logprobs"]],
            )
            # missing key defaults to the base model, matching
            # _validate_session_blob's read of the same field
            adapter = int(blob.get("adapter", 0))
            # adopt the source's RNG stream (see export_session):
            # bit-exact sampled continuations on an RNG-fresh replica,
            # distribution-preserving otherwise, and identical on
            # op-stream followers. Parsed HERE — wrap_key_data on a
            # truncated payload must fail before registration, like
            # every other malformed field
            rng_key = None
            if blob.get("rng") is not None:
                rng_key = jax.random.wrap_key_data(
                    jnp.asarray(wire_to_array(blob["rng"]))
                )
        except Exception as e:  # noqa: BLE001 - re-raised as ValueError
            # the blob passed the signature checks but its payload is
            # missing/corrupt (truncated base64, absent key): the
            # allocated table was never registered, so release it HERE
            # — repeated malformed imports must not shrink the pool
            self.kv.release(table)
            raise ValueError(
                f"malformed session blob payload: {e!r}"
            ) from None
        rid = self._next_id
        self._next_id += 1
        req.request_id = rid
        self._tables[rid] = table
        self.parked[rid] = _Parked(req, stripe, draft_stripe, length,
                                   adapter=adapter)
        if rng_key is not None:
            self._rng = rng_key
        self.imported_total += 1
        return rid

    def radix_digest(self, max_paths: int = 32) -> dict:
        """Hashed hot-prefix summary for the fleet router (rides
        ``/v1/stats`` under ``radix.digest``): the granule size plus
        the most-recently-used cached paths as stable granule-hash
        chains. The router shadow-indexes these per replica and routes
        a prompt to the replica already holding its longest prefix —
        without raw tokens ever leaving the replica."""
        return {
            "granule": self.radix_granule,
            "paths": self.radix.hot_paths(max_paths),
        }

    def cache_poisoned(self) -> bool:
        """True when a donated cache buffer was consumed by a FAILED
        jitted call — the state :meth:`recover` exists for. Checked
        instead of assumed so a host-side error (validation bug, bad
        sampling input) doesn't needlessly nuke live slots."""
        import jax

        trees = [self.cache]
        if self.draft_model is not None:
            trees.append(self.draft_cache)
        return any(
            getattr(leaf, "is_deleted", lambda: False)()
            for t in trees for leaf in jax.tree.leaves(t)
        )

    def recover(self) -> List[int]:
        """Rebuild device decode state after a failed jitted call.

        The cache-transforming jits donate their cache argument, so a
        call that raises mid-flight (transient OOM, backend error)
        leaves ``self.cache`` consumed — without this, every later
        decode raises "Array has been deleted" forever and a
        catch-and-continue caller (the API scheduler) spins dead.
        Drops every live slot (their KV stripes are gone with the old
        cache) and returns their request ids so the caller can fail
        those requests; zeroed caches and replicated decode state are
        rebuilt, already-delivered ``finished`` results and registered
        prefix stripes survive (stripes are independent copies, never
        donated). Single-process recovery: a multi-host driver must
        broadcast the reset through its op stream instead."""
        import jax.numpy as jnp

        # an in-flight block's outputs died with the old cache's lineage
        self._pending_block = None
        self._pending_spec = None
        self.last_dispatch_landed = None
        lost = [r.request_id for r in self.slots.values()]
        for rid in lost:
            self._release_table(rid)
        self.slots.clear()
        self.cache = self.model.init_cache(
            self.max_batch, self.max_len, quant=self.kv_quant
        )
        self.lengths = jnp.zeros(self.max_batch, jnp.int32)
        self.last_token = jnp.zeros(self.max_batch, jnp.int32)
        if self.track_seen:
            self.seen = jnp.zeros_like(self.seen)
        if self.draft_model is not None:
            self.draft_cache = self.draft_model.init_cache(
                self.max_batch, self.max_len
            )
        if self.mesh is not None:
            self._shard_over(self.mesh)
            if self.draft_model is not None:
                self.draft_params, self.draft_cache = (
                    self._shard_model_state(
                        self.mesh, self.draft_model, self.draft_params,
                        self.draft_cache,
                    )
                )
        return lost

    def _check_capacity(self, n: int) -> None:
        """Host-side admission capacity check (shared with the
        multi-host driver's pre-broadcast validation)."""
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if self.free_slots() < n:
            raise RuntimeError(
                f"need {n} free slots, have {self.free_slots()}"
            )

    def _free_slot_indices(self) -> List[int]:
        """THE slot-allocation policy (lowest index first) — shared by
        single admission, fork admission, and prefix registration so
        the three cannot drift."""
        return [i for i in range(self.max_batch) if i not in self.slots]

    def _first_free_slot(self, why: str) -> int:
        free = self._free_slot_indices()
        if not free:
            raise RuntimeError(why)
        return free[0]

    def _check_prompt_fits(self, prompt: List[int]) -> int:
        """Validate the prompt against the cache; returns chunk count."""
        if not prompt:
            raise ValueError("empty prompt")
        P = self.prefill_len
        n_chunks = -(-len(prompt) // P)
        # every chunk write must land fully inside the stripe: a clamped
        # dynamic_update_slice would silently shift into earlier positions
        if n_chunks * P > self.max_len or len(prompt) > self.max_len - 1:
            raise ValueError(
                f"prompt length {len(prompt)} cannot fit max_len "
                f"{self.max_len} (chunked at {P})"
            )
        return n_chunks

    def _prefill_chunks(self, slot: int, prompt: List[int],
                        start_chunk: int = 0, adapter: int = 0):
        """Run chunks [start_chunk, n) of ``prompt`` into a slot's cache
        stripe (target + draft); returns the last chunk's logits."""
        P = self.prefill_len
        n_chunks = -(-len(prompt) // P)
        chunk_logits = None
        aidx = jnp.full((1,), adapter, jnp.int32)
        # NB: registered-prefix stripes are base-model KV; admission
        # skips prefix reuse for adapter requests (add_request_n)
        for i in range(start_chunk, n_chunks):
            chunk = prompt[i * P:(i + 1) * P]
            padded = jnp.asarray(
                chunk + [0] * (P - len(chunk)), jnp.int32
            )[None]
            self.cache, chunk_logits = self._prefill(
                self.params, self.cache, padded, slot, i * P, aidx
            )
            if self.draft_model is not None:
                self.draft_cache = self._draft_prefill(
                    self.draft_params, self.draft_cache, padded, slot,
                    i * P,
                )
        return chunk_logits

    def _match_prefix(self, prompt: List[int]) -> Optional[RadixMatch]:
        """Longest radix-cached strict prefix of ``prompt``, granule-
        aligned and capped so at least one chunk still prefills (its
        logits seed the first sampled token — the strict-prefix rule
        the exact-match cache had). PURE: no LRU touch, so the
        scheduler can call it while planning without diverging
        op-stream followers (the admission op touches)."""
        g = self.radix_granule
        limit = ((len(prompt) - 1) // g) * g
        if limit <= 0:
            return None
        m = self.radix.match(prompt, limit)
        return m if m.length else None

    def admit_block_cost(self, prompt: List[int], n: int = 1,
                         adapter: int = 0,
                         match=_MATCH_UNSET) -> int:
        """Pool blocks admitting this request will charge — THE shared
        admission cost model (``can_admit``, ``_alloc_tables``'s
        reclaim, and the scheduler's burst planning and block-pressure
        guards all use it, so headroom math charges only the NON-SHARED
        suffix of a radix hit instead of the whole prompt). Matched
        blocks fork at zero pool cost; a match ending inside a block
        pays the one boundary copy-on-write ensure() will charge;
        forks pay one boundary block each as before. Callers that
        already walked the tree pass ``match=`` (the scheduler's
        planner — one walk per request per round, not four)."""
        if match is _MATCH_UNSET:
            match = self._match_prefix(prompt) if adapter == 0 else None
        shared = self.kv.blocks_for(match.length) if match else 0
        cow = 1 if match and match.length % self.kv.block_size else 0
        return (self.kv.blocks_for(len(prompt) + 1) - shared + cow
                + (n - 1))

    def match_reserve(self, match) -> int:
        """Evictable-supply blocks admitting through ``match`` takes
        OFF the table: _alloc_tables locks the matched path before
        reclaiming, so its pool blocks — counted in
        ``evictable_blocks()`` while unlocked — stop being
        reclaimable the moment this admission starts. Every
        supply-side check (can_admit, the scheduler's burst ledger and
        block-pressure guards) must charge this reserve alongside
        ``admit_block_cost``, or a prompt whose own matched path IS
        most of the evictable supply would pass the check and then
        hard-fail allocation (conservative when the path is already
        locked by another table — the request just waits a round)."""
        if match is None:
            return 0
        return sum(nd.pool_block_count() for nd in match.path)

    def _reclaim_for(self, need_blocks: int) -> None:
        """Free pool blocks by LRU-evicting unreferenced radix nodes —
        the deterministic engine-side half of "cached blocks count as
        free": callers that observed ``evictable_blocks`` in their
        headroom math call this inside the admission/decode op, so
        op-stream followers evict the identical nodes."""
        deficit = need_blocks - self.kv.free_blocks()
        if deficit > 0:
            self.radix.reclaim(deficit)

    def _write_match_stripes(self, path: List[RadixNode], length: int,
                             slot: int) -> None:
        """Write the matched path's per-granule KV stripes into a
        slot's cache rows (target + draft) up to ``length`` — the
        radix-hit replacement for re-running that prefix's prefill
        chunks. One compiled write program per stripe length (the
        granule); offsets are traced."""
        g = self.radix_granule
        for node in path:
            for i, stripe in enumerate(node.stripes):
                off = node.start + i * g
                if off >= length:
                    return
                self.cache = self._write_stripe(self.cache, stripe,
                                                slot, off)
                if (self.draft_model is not None
                        and node.draft_stripes is not None):
                    self.draft_cache = self._write_stripe(
                        self.draft_cache, node.draft_stripes[i], slot,
                        off,
                    )

    def _read_granule_stripes(self, slot: int, start_g: int,
                              end_g: int):
        """(stripes, draft_stripes) for granules [start_g, end_g) of a
        slot's cache rows — the read half of radix insertion and
        registration."""
        g = self.radix_granule
        stripes = []
        dstripes = [] if self.draft_model is not None else None
        for gi in range(start_g, end_g):
            stripes.append(
                self._read_stripe(self.cache, slot, gi * g, length=g)
            )
            if dstripes is not None:
                dstripes.append(
                    self._read_stripe(self.draft_cache, slot, gi * g,
                                      length=g)
                )
        return stripes, dstripes

    def _radix_insert(self, slot: int, req: "_Slot") -> None:
        """Insert a finishing request's prompt (and, with
        ``radix_decoded``, its decoded tokens) into the radix tree so
        the NEXT prompt sharing the prefix skips that prefill — the
        no-registration half of the prefix cache. Called after the
        request's own table released (its freed blocks are exactly the
        room the new node wants). Best-effort: insertion never evicts
        anything and never fails the completion path."""
        if not self.radix_cache:
            return
        if self._slot_adapter_host.get(slot, 0) != 0:
            # adapter KV must never pollute the base-model tree (the
            # same rule that makes adapter requests skip prefix reuse)
            return
        g = self.radix_granule
        toks = list(req.prompt)
        if self.radix_decoded:
            toks += req.generated
            # generated[-1] is the pending last_token, not yet written
            # to the cache (same bound preempt_slot rounds from)
            limit = len(toks) - 1
        else:
            limit = len(req.prompt)
        # a stored prefix only ever hits a strictly-longer prompt whose
        # remainder chunk must still fit the cache (the registration
        # bound, applied to organic inserts too)
        limit = min(limit, self.max_len - self.prefill_len)
        L = (limit // g) * g
        if L < g:
            return
        granules = self.radix.granules_of(toks, L)
        try:
            parent, matched = self.radix.ensure_path(granules)
            if matched == len(granules):
                self.radix.touch(parent)
                return
            cost = (self.kv.blocks_for(L)
                    - self.kv.blocks_for(matched * g)
                    + (1 if (matched * g) % self.kv.block_size else 0))
            if cost > self.kv.free_blocks():
                return           # full pool: cache only what fits free
            stripes, dstripes = self._read_granule_stripes(
                slot, matched, len(granules)
            )
            node = self.radix.add_child(parent, granules[matched:])
            node.stripes = stripes
            node.draft_stripes = dstripes
            self.prefix_inserted += 1
        except Exception as e:  # noqa: BLE001 - cache fill is optional
            # single-host: a failed stripe read (transient device
            # error) aborts THIS insert, never the completion that
            # triggered it — log so a persistently failing cache is
            # visible, keep serving. Multi-host: swallowing would leave
            # THIS replica's tree short one node while the others
            # inserted — later matches/evictions would then dispatch
            # different device ops per replica and deadlock the
            # collectives; die loudly instead so the pod restarts
            # (the follower's RuntimeError-subclass policy).
            if self._multiproc:
                raise
            log.warning("radix insert skipped: %s", e)

    def register_prefix(self, prefix: List[int]) -> None:
        """Pre-insert ``prefix`` into the radix prefix cache as a
        REGISTERED path: prefilled once (unless the tree already holds
        it), pinned outside the allocatable pool, exempt from LRU
        eviction until :meth:`drop_prefix`.

        DEPRECATED as an optimization step — since the radix cache
        (PR 11) every completion inserts its prompt automatically, so
        organically shared prefixes are cached with no registration.
        Kept one release as a thin wrapper for operators who want a
        prefix pinned before the first request arrives (and for the
        existing ``POST /v1/prefixes`` surface); see docs/SERVING.md
        "Radix prefix cache".

        Constraints unchanged: the length must be a multiple of
        ``prefill_len`` (granule-floored internally when the radix
        granule is coarser), short enough that a strictly-longer
        prompt still fits the cache, and a free slot must exist to
        prefill through when the path is not already cached."""
        key = tuple(prefix)
        if key in self.prefixes:
            return
        self._drain_pending()
        self._validate_prefix(prefix)
        if self.fault_hook is not None:
            self.fault_hook("prefill")
        with get_tracer().span(
            "engine.prefix_register", tokens=len(prefix),
        ):
            self._register_prefix_inner(prefix, key)

    def _register_prefix_inner(self, prefix: List[int], key) -> None:
        g = self.radix_granule
        reg_len = (len(prefix) // g) * g
        granules = self.radix.granules_of(prefix, reg_len)
        parent, matched = self.radix.ensure_path(granules)
        if matched == len(granules):
            # the organic cache already learned this prefix: just pin
            # it (no prefill, no new blocks)
            node = parent
        else:
            slot = self._first_free_slot(
                "no free slots to prefill the prefix"
            )
            if matched:
                # cached head: write its stripes, prefill the rest
                self._write_match_stripes(
                    self.radix.path_of(parent), matched * g, slot
                )
            self._prefill_chunks(slot, list(prefix[:reg_len]),
                                 start_chunk=matched * g
                                 // self.prefill_len)
            stripes, dstripes = self._read_granule_stripes(
                slot, matched, len(granules)
            )
            # pinned: registered segments live OUTSIDE the allocatable
            # pool (like the pre-radix stripe cache), so registration
            # never shrinks the capacity admission reasons over
            node = self.radix.add_child(parent, granules[matched:],
                                        pinned=True)
            node.stripes = stripes
            node.draft_stripes = dstripes
        node.registered = True
        # the whole path is now structurally un-evictable: move its
        # pool blocks outside the allocatable pool (adopting an
        # organically-cached path must not silently shrink the
        # capacity admission reasons over — the "registration never
        # costs serving capacity" contract the pre-radix pin() kept)
        self.radix.pin_path(node)
        self.radix.touch(node)
        self.prefixes[key] = node

    def _validate_prefix(self, prefix: List[int]) -> None:
        """Host-side registration checks, raised BEFORE any device op
        (so a multi-host driver can validate before broadcasting)."""
        P = self.prefill_len
        if not prefix or len(prefix) % P:
            raise ValueError(
                f"prefix length {len(prefix)} must be a non-zero "
                f"multiple of prefill_len {P}"
            )
        # a hit needs a strictly-longer prompt, whose remainder chunk
        # must also fit the cache: len(prefix) + one more chunk <= max_len
        # (a looser bound would admit stripes no prompt can ever use)
        if len(prefix) + P > self.max_len:
            raise ValueError(
                f"prefix length {len(prefix)} leaves no room for a "
                f"longer prompt's remainder chunk in max_len "
                f"{self.max_len} (chunked at {P})"
            )
        if len(self.prefixes) >= self.max_prefixes:
            raise RuntimeError(
                f"prefix cache full ({self.max_prefixes}); drop_prefix "
                "one first (each stored stripe pins HBM)"
            )
        # a free slot is only needed when something must PREFILL: a
        # path the organic cache fully holds just gets pinned
        # (match() is pure, so pre-broadcast validation stays safe)
        g = self.radix_granule
        reg_len = (len(prefix) // g) * g
        if self.radix.match(list(prefix), reg_len).length < reg_len:
            self._first_free_slot("no free slots to prefill the prefix")

    def drop_prefix(self, prefix: List[int]) -> bool:
        """Un-register a prefix: its tree path loses eviction
        exemption, and whatever of it no live table references is
        evicted NOW (pinned segment blocks unpin; copies shared into
        live tables survive until those tables release them). Organic
        descendants that grew under the registered path stay cached as
        ordinary LRU-evictable nodes."""
        key = tuple(prefix)
        node = self.prefixes.pop(key, None)
        if node is None:
            return False
        node.registered = False
        cur = node
        while (cur is not None and cur is not self.radix.root
               and not cur.children and cur.locks == 0
               and not cur.registered):
            parent = cur.parent
            self.radix.evict(cur)
            cur = parent
        return True

    @staticmethod
    def _normalize_stop(stop) -> List[List[int]]:
        """``stop`` → list of non-empty token-id sequences: accepts
        None, one flat sequence ([1, 2]), or a list of sequences."""
        if not stop:
            return []
        if all(isinstance(t, int) for t in stop):
            stop = [stop]
        out = []
        for seq in stop:
            if (not isinstance(seq, (list, tuple)) or not seq
                    or not all(isinstance(t, int) for t in seq)):
                raise ValueError(
                    "stop must be a token-id sequence or a list of them"
                )
            out.append(list(seq))
        return out

    def add_request(self, prompt: List[int], stop=None,
                    adapter: int = 0) -> int:
        """Admit a prompt; returns the request id. Raises when the batch
        is full (callers queue) or the prompt cannot fit the cache.

        Prompts longer than ``prefill_len`` are prefilled in
        ``prefill_len``-sized chunks — every chunk reuses the same
        compiled program, so long prompts cost chunk-count invocations,
        never a recompile. A prompt starting with a registered prefix
        (:meth:`register_prefix`) skips that prefix's chunks: the stored
        stripe is copied in and prefill resumes at the boundary.

        ``stop``: token-id sequence(s); generation finishes (reason
        ``"stop"``) when one appears in the output, which is truncated
        to exclude it. Checked host-side after every step/block — the
        compiled programs don't change.

        ``adapter``: which LoRA adapter this request flows through
        (1-based into the engine's ``lora_adapters``; 0 = the base
        model). Requires the engine to have been built with adapters."""
        return self.add_request_n(prompt, 1, stop=stop,
                                  adapter=adapter)[0]

    def add_request_n(self, prompt: List[int], n: int,
                      stop=None, adapter: int = 0) -> List[int]:
        """Admit ``n`` parallel samples of one prompt (OpenAI ``n``):
        the prompt is prefilled ONCE, its KV stripe is copied to the
        other n-1 slots (pure HBM copies — the same stripe kernels
        prefix caching uses), and each fork samples its own first
        token. Returns the n request ids; all-or-nothing on capacity.

        With ``temperature == 0`` every fork produces the same greedy
        chain (allowed, like OpenAI, but pointless); at temperature > 0
        forks diverge from the first sampled token on (independent
        Gumbel noise per batch row)."""
        # the span joins the caller's ambient trace (the API scheduler
        # binds the request's trace id around admission), so prefill
        # cost is attributable to the request that paid it
        self._drain_pending()
        with get_tracer().span(
            "engine.prefill", tokens=len(prompt), n=n,
        ) as sp:
            rids = self._add_request_n_inner(prompt, n, stop, adapter, sp)
        return rids

    def _adopt_radix_locks(self, pref: Optional[RadixMatch],
                           rids: List[int]) -> None:
        """Hand the path locks :meth:`_alloc_tables` took to the
        admitted rids (released per rid in :meth:`_release_table`). A
        rid that finished ON admission (max_len edge) already released
        its table, so its lock unwinds here instead of leaking."""
        if pref is None:
            return
        deepest = pref.path[-1]
        for rid in rids:
            if rid in self._tables:
                self._radix_locks[rid] = (deepest, pref.length)
            else:
                self.radix.unlock(deepest)

    def _alloc_tables(self, prompt_len: int, n: int,
                      pref: Optional[RadixMatch],
                      prelocked: bool = False):
        """Block tables for an n-way admission, all-or-nothing. The
        first table forks the matched radix path's segment tables (its
        blocks are copy-on-write shared — zero pool cost until
        divergence); forks 2..n share the first table's blocks the same
        way. Locks the matched path n times first (one per fork, so
        reclaim — here or in a later admission — can never evict a node
        a table is about to reference); the rids adopt the locks at
        registration, and every failure path unlocks.
        ``prelocked=True`` means the caller took (and owns unwinding)
        those locks already — the BURST path must lock EVERY
        co-admitted request's path before ANY request's reclaim runs,
        or request i's reclaim could evict the node request j>i
        matched and j would fork a dead table."""
        from instaslice_tpu.serving.kvcache import BlockPoolExhausted

        tables: List[BlockTable] = []
        locked = 0
        node = pref.path[-1] if pref is not None else None
        try:
            if node is not None and not prelocked:
                for _ in range(n):
                    self.radix.lock(node)
                locked = n
            shared = self.kv.blocks_for(pref.length) if pref else 0
            cow = (1 if pref and pref.length % self.kv.block_size
                   else 0)
            # cached-but-unreferenced radix blocks count as free in
            # can_admit's math — make that true before allocating
            self._reclaim_for(
                self.kv.blocks_for(prompt_len + 1) - shared + cow
                + (n - 1)
            )
            t0 = (self.kv.fork(pref.path[-1].table, pref.length)
                  if pref is not None else self.kv.allocate(0))
            tables.append(t0)
            # +1: admission samples each request's first token
            self.kv.ensure(t0, prompt_len + 1)
            for _ in range(n - 1):
                # forks share the PROMPT's blocks only — their first
                # sampled tokens diverge, so the boundary block copies
                # right here rather than pretending to be shared
                t = self.kv.fork(t0, prompt_len)
                tables.append(t)
                self.kv.ensure(t, prompt_len + 1)
        except BlockPoolExhausted as e:
            for t in tables:
                self.kv.release(t)
            for _ in range(locked):
                self.radix.unlock(node)
            raise RuntimeError(
                f"kv block pool cannot admit this request: {e} "
                "(shed parked state or wait for a release)"
            ) from None
        return tables

    def _add_request_n_inner(self, prompt: List[int], n: int,
                             stop, adapter: int, sp) -> List[int]:
        stop = self._normalize_stop(stop)
        if not 0 <= adapter <= self.n_adapters:
            raise ValueError(
                f"adapter {adapter} out of range (engine has "
                f"{self.n_adapters} adapter(s); 0 = base)"
            )
        self._check_prompt_fits(prompt)
        self._check_capacity(n)
        # radix-cached stripes hold BASE-model KV: an adapter request
        # must recompute its whole prompt through the adapter (reusing
        # base KV would serve a silent base/adapter hybrid)
        t_match = time.perf_counter()
        pref = self._match_prefix(prompt) if adapter == 0 else None
        get_tracer().record(
            "engine.radix_match",
            (time.perf_counter() - t_match) * 1e3,
            matched=pref.length if pref else 0, tokens=len(prompt),
        )
        tables = self._alloc_tables(len(prompt), n, pref)
        try:
            rids = self._admit_with_tables(
                prompt, n, stop, adapter, sp, pref, tables
            )
        except BaseException:
            # a failed admission (injected fault, device error) must
            # not leak the blocks it reserved — the caller's recovery
            # path only releases REGISTERED tables (release is
            # idempotent; the path locks _alloc_tables took unwind too)
            for t in tables:
                self.kv.release(t)
            if pref is not None:
                for _ in range(n):
                    self.radix.unlock(pref.path[-1])
            raise
        self._adopt_radix_locks(pref, rids)
        return rids

    def _admit_with_tables(self, prompt: List[int], n: int, stop,
                           adapter: int, sp, pref,
                           tables: List[BlockTable]) -> List[int]:
        if self.fault_hook is not None:
            self.fault_hook("prefill")
        slots = self._free_slot_indices()[:n]
        first = slots[0]
        for s in slots:
            self._slot_adapter_host[s] = adapter
        if self.lora is not None:
            self.slot_adapter = self.slot_adapter.at[
                jnp.asarray(slots)
            ].set(adapter)
        start_chunk = 0
        if pref is not None:
            sp.attrs["prefix_hit"] = str(pref.length)
            self._write_match_stripes(pref.path, pref.length, first)
            start_chunk = pref.length // self.prefill_len
            self.radix.touch(pref.path[-1])
            self.prefix_hits += 1
            self.prefix_tokens_saved += pref.length
        elif adapter == 0:
            self.prefix_misses += 1
        chunk_logits = self._prefill_chunks(first, prompt, start_chunk,
                                            adapter=adapter)
        last_logits = chunk_logits[(len(prompt) - 1) % self.prefill_len]
        if len(slots) > 1:
            # fork: copy the prefilled stripe to the other slots — the
            # stripe is chunk-padded, so reads share prefix caching's
            # compiled shape family
            stripe_len = (
                -(-len(prompt) // self.prefill_len) * self.prefill_len
            )
            stripe = self._read_stripe(self.cache, first, 0,
                                       length=stripe_len)
            d_stripe = None
            if self.draft_model is not None:
                d_stripe = self._read_stripe(self.draft_cache, first, 0,
                                             length=stripe_len)
            for s in slots[1:]:
                self.cache = self._write_stripe(self.cache, stripe, s,
                                                0)
                if d_stripe is not None:
                    self.draft_cache = self._write_stripe(
                        self.draft_cache, d_stripe, s, 0
                    )
        if self.track_seen:
            # fresh slots: clear whatever the previous occupant saw
            # (the single reset point for every release path), then
            # the prompt is "seen" before the FIRST token samples —
            # one vectorized scatter for all forks (they share it)
            rows = jnp.asarray(slots)
            pt = jnp.asarray(prompt, jnp.int32)
            self.seen = self.seen.at[rows].set(False)
            self.seen = self.seen.at[rows[:, None], pt[None, :]].set(True)
        # one sample call for all forks: the (n, vocab) rows are
        # identical, but Gumbel noise is independent per row, so forks
        # diverge at temperature > 0
        toks, lps = self._sample(
            jnp.broadcast_to(last_logits[None],
                             (len(slots),) + last_logits.shape),
            rows=slots,
        )
        if self.track_seen:
            self.seen = self.seen.at[jnp.asarray(slots), toks].set(True)
        rids = []
        for i, s in enumerate(slots):
            rid = self._next_id
            self._next_id += 1
            self.last_token = self.last_token.at[s].set(toks[i])
            self.lengths = self.lengths.at[s].set(len(prompt))
            self.slots[s] = _Slot(rid, list(prompt), [int(toks[i])],
                                  list(stop), logprobs=[float(lps[i])])
            self._tables[rid] = tables[i]
            self.tokens_generated += 1
            self._maybe_finish(s)
            rids.append(rid)
        return rids

    def add_requests(self, reqs: List[AdmissionRequest]) \
            -> List[List[int]]:
        """Admit a BURST of requests through ONE dispatch chain: every
        chunk round prefills one ``(P, prefill_len)`` multi-slot batch
        (P bucketed to powers of two, so a burst of B admissions costs
        ``max(chunks)`` bucketed dispatches instead of ``sum(chunks)``
        sequential ones). Token-identical to admitting the same
        requests one by one in order — rows are independent, and
        first-token sampling runs per request in burst order so even
        the RNG stream matches the sequential path. Returns one rid
        list per request, 1:1 with ``reqs``; all-or-nothing on
        capacity like :meth:`add_request_n`.

        On a draft-carrying engine the TARGET chunks still batch; the
        draft's chunk prefills dispatch per-row inside each round (the
        draft is the cheap model — its dispatch count is not the
        bottleneck the batched program exists to cut), leaving the
        draft cache byte-identical to sequential admission. Falls back
        to sequential admission when ``batched_prefill`` is off or the
        burst is a single request."""
        reqs = [r if isinstance(r, AdmissionRequest)
                else AdmissionRequest(**r) for r in reqs]
        if not self.batched_prefill or len(reqs) <= 1:
            return [self.add_request_n(r.prompt, r.n, stop=r.stop,
                                       adapter=r.adapter) for r in reqs]
        self._drain_pending()
        with get_tracer().span(
            "engine.prefill_batch", reqs=len(reqs),
            tokens=sum(len(r.prompt) for r in reqs),
        ) as sp:
            return self._add_requests_inner(reqs, sp)

    def _add_requests_inner(self, reqs: List[AdmissionRequest], sp) \
            -> List[List[int]]:
        # host-side validation for the WHOLE burst before any device op
        # or table allocation (all-or-nothing: one bad request rejects
        # the burst — callers pre-screen per request where that matters)
        stops = [self._normalize_stop(r.stop) for r in reqs]
        for r in reqs:
            if not 0 <= r.adapter <= self.n_adapters:
                raise ValueError(
                    f"adapter {r.adapter} out of range (engine has "
                    f"{self.n_adapters} adapter(s); 0 = base)"
                )
            self._check_prompt_fits(r.prompt)
        self._check_capacity(sum(r.n for r in reqs))
        t_match = time.perf_counter()
        prefs = [self._match_prefix(r.prompt) if r.adapter == 0
                 else None for r in reqs]
        get_tracer().record(
            "engine.radix_match",
            (time.perf_counter() - t_match) * 1e3,
            matched=sum(p.length for p in prefs if p),
            tokens=sum(len(r.prompt) for r in reqs), reqs=len(reqs),
        )
        all_tables: List[List[BlockTable]] = []
        # lock EVERY request's matched path BEFORE any allocation: a
        # co-admitted request's reclaim must never LRU-evict a node a
        # later request of the same burst is about to fork (it would
        # inherit a released table and skip prefill with no stripes —
        # silently wrong KV)
        for r, pref in zip(reqs, prefs):
            if pref is not None:
                for _ in range(r.n):
                    self.radix.lock(pref.path[-1])
        try:
            for r, pref in zip(reqs, prefs):
                all_tables.append(
                    self._alloc_tables(len(r.prompt), r.n, pref,
                                       prelocked=True)
                )
            out = self._admit_burst(reqs, stops, prefs, all_tables, sp)
        except BaseException:
            # nothing admitted on failure: release every table the
            # burst reserved (release is idempotent, so tables that
            # made it into _tables before a late failure just free)
            # and unwind EVERY pre-taken path lock
            for tables in all_tables:
                for t in tables:
                    self.kv.release(t)
            for r, pref in zip(reqs, prefs):
                if pref is not None:
                    for _ in range(r.n):
                        self.radix.unlock(pref.path[-1])
            raise
        for pref, rids in zip(prefs, out):
            self._adopt_radix_locks(pref, rids)
        return out

    def _admit_burst(self, reqs, stops, prefs, all_tables, sp) \
            -> List[List[int]]:
        if self.fault_hook is not None:
            self.fault_hook("prefill")
        P = self.prefill_len
        free = self._free_slot_indices()
        slots_per: List[List[int]] = []
        i = 0
        for r in reqs:
            # contiguous low-first assignment == what sequential
            # add_request_n calls would pick (slot-allocation policy
            # must not drift between the two admission paths)
            slots_per.append(free[i:i + r.n])
            i += r.n
        flat_slots = [s for ss in slots_per for s in ss]
        flat_adapt = [r.adapter for r, ss in zip(reqs, slots_per)
                      for _ in ss]
        for s, a in zip(flat_slots, flat_adapt):
            self._slot_adapter_host[s] = a
        if self.lora is not None:
            self.slot_adapter = self.slot_adapter.at[
                jnp.asarray(flat_slots)
            ].set(jnp.asarray(flat_adapt, jnp.int32))
        # radix-matched stripes land before any chunk round touches the
        # slot — a burst's requests join the chunk rounds mid-tree,
        # each at its own matched depth
        start_chunks: List[int] = []
        for r, pref, ss in zip(reqs, prefs, slots_per):
            sc = 0
            if pref is not None:
                self._write_match_stripes(pref.path, pref.length,
                                          ss[0])
                sc = pref.length // P
                self.radix.touch(pref.path[-1])
                self.prefix_hits += 1
                self.prefix_tokens_saved += pref.length
            elif r.adapter == 0:
                self.prefix_misses += 1
            start_chunks.append(sc)
        # chunk rounds: each request advances ONE chunk per round
        # (chunk j+1 attends chunk j's KV), all participants in one
        # bucketed dispatch — a burst of B same-length admissions is
        # max-chunks dispatches, not B separate chains
        cursors = list(start_chunks)
        n_chunks = [-(-len(r.prompt) // P) for r in reqs]
        last_logits: List[Optional[jax.Array]] = [None] * len(reqs)
        rounds = 0
        while True:
            group = [gi for gi in range(len(reqs))
                     if cursors[gi] < n_chunks[gi]]
            if not group:
                break
            rounds += 1
            max_rows = (self._prefill_buckets[-1]
                        if self._prefill_buckets else 1)
            for gstart in range(0, len(group), max_rows):
                part = group[gstart:gstart + max_rows]
                if len(part) == 1:
                    # a lone row (uneven chunk drain): the per-slot
                    # prefill program already compiled for this exact
                    # shape — no bucket-1 program needed, ever
                    ri = part[0]
                    c = reqs[ri].prompt[cursors[ri] * P:
                                        (cursors[ri] + 1) * P]
                    padded = jnp.asarray(
                        c + [0] * (P - len(c)), jnp.int32
                    )[None]
                    self.cache, logits1 = self._prefill(
                        self.params, self.cache, padded,
                        slots_per[ri][0], cursors[ri] * P,
                        jnp.full((1,), reqs[ri].adapter, jnp.int32),
                    )
                    if self.draft_model is not None:
                        self.draft_cache = self._draft_prefill(
                            self.draft_params, self.draft_cache,
                            padded, slots_per[ri][0], cursors[ri] * P,
                        )
                    self.prefill_rows += 1
                    if cursors[ri] == n_chunks[ri] - 1:
                        last_logits[ri] = logits1
                    continue
                bucket = next(b for b in self._prefill_buckets
                              if b >= len(part))
                # padding rows duplicate the last real row — identical
                # values scattered to the same slot, idempotent
                rows = part + [part[-1]] * (bucket - len(part))
                toks = []
                for ri in rows:
                    c = reqs[ri].prompt[cursors[ri] * P:
                                        (cursors[ri] + 1) * P]
                    toks.append(c + [0] * (P - len(c)))
                self.cache, logits = self._prefill_batch(
                    self.params, self.cache,
                    jnp.asarray(toks, jnp.int32),
                    jnp.asarray([slots_per[ri][0] for ri in rows],
                                jnp.int32),
                    jnp.asarray([cursors[ri] * P for ri in rows],
                                jnp.int32),
                    jnp.asarray([reqs[ri].adapter for ri in rows],
                                jnp.int32),
                )
                self.prefill_batches += 1
                self.prefill_rows += len(part)
                self.prefill_pad_rows += bucket - len(part)
                self._prefill_occ.append(len(part) / bucket)
                if self.draft_model is not None:
                    # the draft cache must hold every prompt too: one
                    # per-row dispatch each (the draft is cheap; its
                    # content is byte-identical to sequential
                    # admission's _prefill_chunks ordering)
                    for ri in part:
                        c = reqs[ri].prompt[cursors[ri] * P:
                                            (cursors[ri] + 1) * P]
                        self.draft_cache = self._draft_prefill(
                            self.draft_params, self.draft_cache,
                            jnp.asarray(
                                c + [0] * (P - len(c)), jnp.int32
                            )[None],
                            slots_per[ri][0], cursors[ri] * P,
                        )
                for row_i, ri in enumerate(part):
                    if cursors[ri] == n_chunks[ri] - 1:
                        last_logits[ri] = logits[row_i]
            for ri in group:
                cursors[ri] += 1
        sp.attrs["rounds"] = str(rounds)
        # per-request device tail IN BURST ORDER: fork stripe copies,
        # seen-set resets, first-token sampling — the exact sequence
        # (and RNG stream) sequential admissions produce
        toks_per: List[jax.Array] = []
        lps_per: List[jax.Array] = []
        for ri, r in enumerate(reqs):
            ss = slots_per[ri]
            if r.n > 1:
                stripe = self._read_stripe(
                    self.cache, ss[0], 0, length=n_chunks[ri] * P
                )
                d_stripe = None
                if self.draft_model is not None:
                    d_stripe = self._read_stripe(
                        self.draft_cache, ss[0], 0,
                        length=n_chunks[ri] * P,
                    )
                for s in ss[1:]:
                    self.cache = self._write_stripe(self.cache, stripe,
                                                    s, 0)
                    if d_stripe is not None:
                        self.draft_cache = self._write_stripe(
                            self.draft_cache, d_stripe, s, 0
                        )
            if self.track_seen:
                rows = jnp.asarray(ss)
                pt = jnp.asarray(r.prompt, jnp.int32)
                self.seen = self.seen.at[rows].set(False)
                self.seen = self.seen.at[
                    rows[:, None], pt[None, :]
                ].set(True)
            ll = last_logits[ri][(len(r.prompt) - 1) % P]
            t_, l_ = self._sample(
                jnp.broadcast_to(ll[None], (len(ss),) + ll.shape),
                rows=ss,
            )
            if self.track_seen:
                self.seen = self.seen.at[jnp.asarray(ss), t_].set(True)
            toks_per.append(t_)
            lps_per.append(l_)
        # registration: pure host bookkeeping, after every device op
        out: List[List[int]] = []
        for ri, r in enumerate(reqs):
            rids: List[int] = []
            for k, s in enumerate(slots_per[ri]):
                rid = self._next_id
                self._next_id += 1
                self.last_token = self.last_token.at[s].set(
                    toks_per[ri][k]
                )
                self.lengths = self.lengths.at[s].set(len(r.prompt))
                self.slots[s] = _Slot(
                    rid, list(r.prompt), [int(toks_per[ri][k])],
                    list(stops[ri]),
                    logprobs=[float(lps_per[ri][k])],
                )
                self._tables[rid] = all_tables[ri][k]
                self.tokens_generated += 1
                self._maybe_finish(s)
                rids.append(rid)
            out.append(rids)
        return out

    def step(self) -> Dict[int, int]:
        """One decode step for every live slot; returns request id → new
        token. Slots hitting eos/max_len move to ``finished``."""
        self._drain_pending()
        if not self.slots:
            return {}
        with get_tracer().span(
            "engine.decode_step", batch=len(self.slots),
        ):
            return self._step_inner()

    def _step_inner(self) -> Dict[int, int]:
        if self.fault_hook is not None:
            self.fault_hook("decode")
        if self.draft_model is not None:
            # keep the draft cache position-complete: it must consume
            # every token the target consumes or later spec_steps attend
            # zero-holes
            self.draft_cache = self._draft_catchup(
                self.draft_params, self.draft_cache,
                self.last_token[:, None], self.lengths,
            )
        # the sampled token for step t is appended at position lengths+1
        # (the prompt's last token sits at lengths-1; sampled continuation
        # enters the cache when it is fed back as input here)
        aidx, single = self._adapter_args()
        self.cache, logits = self._decode(
            self.params, self.cache, self.last_token, self.lengths,
            aidx, single=single,
        )
        toks, lps = self._sample(logits)
        if self.track_seen:
            self.seen = self.seen.at[
                jnp.arange(self.max_batch), toks
            ].set(True)
        # one combined host round-trip (int(toks[slot]) per slot would
        # sync the device once per live slot)
        toks_h, lps_h = jax.device_get((toks, lps))
        self.last_dispatch_landed = time.monotonic()
        out: Dict[int, int] = {}
        for slot, req in list(self.slots.items()):
            t = int(toks_h[slot])
            out[req.request_id] = t
            req.generated.append(t)
            req.logprobs.append(float(lps_h[slot]))
            self.tokens_generated += 1
        self.last_token = toks
        live = jnp.zeros(self.max_batch, jnp.bool_)
        for slot in self.slots:
            live = live.at[slot].set(True)
        self.lengths = jnp.where(live, self.lengths + 1, self.lengths)
        for slot in list(self.slots):
            self._maybe_finish(slot)
        self._sync_tables()
        return out

    def decode_block(self, n_steps: int) -> Dict[int, List[int]]:
        """Run ``n_steps`` decode steps fully on-device (one dispatch, one
        (n_steps, B) readback) and return request id → new tokens.

        EOS inside the block still finishes the slot — tokens past the
        EOS are discarded host-side (the cache positions they occupied are
        never attended by a later occupant: prefill resets the slot's
        length and the cache mask hides everything beyond it). Raises if
        any live slot would run past the cache, so block misuse is loud
        instead of silently clamping writes.

        Split form (the host/device overlap seam the continuous
        scheduler uses): :meth:`decode_block_start` dispatches the
        compiled scan and starts an async device→host copy of the token
        block, :meth:`decode_block_finish` blocks on the copy and does
        the host bookkeeping — between the two the device is computing
        while the host plans the next round. This method is simply
        start + finish."""
        self.decode_block_start(n_steps)
        return self.decode_block_finish()

    def _drain_pending(self) -> None:
        """Land an in-flight decode block before any other engine
        mutation: slot occupancy, tables, and the carry must never be
        touched with a dispatched block's tokens unread. Results go
        through the normal bookkeeping (``finished`` etc.); the
        scheduler never hits this (it always finishes explicitly) —
        this keeps direct engine users safe by construction."""
        if self._pending_block is not None:
            self.decode_block_finish()
        if self._pending_spec is not None:
            self.spec_step_finish()

    def decode_block_start(self, n_steps: int) -> bool:
        """Dispatch ``n_steps`` decode steps WITHOUT blocking on the
        tokens: the compiled scan is enqueued, the (n_steps, B) token
        block's device→host copy starts asynchronously, and the call
        returns while the device computes. Returns False (no dispatch)
        on an empty batch. A second start before the finish lands the
        first block first (one block in flight at a time — the carry
        feeds forward on device, but host bookkeeping is per block)."""
        self._drain_pending()
        if not self.slots:
            return False
        if self.fault_hook is not None:
            self.fault_hook("decode")
        worst = max(
            len(r.prompt) + len(r.generated) for r in self.slots.values()
        )
        if worst + n_steps > self.max_len - 1:
            raise ValueError(
                f"decode_block({n_steps}) would overrun max_len "
                f"{self.max_len} (deepest live slot at {worst})"
            )
        self._rng, sub = jax.random.split(self._rng)
        last_before, lengths_before = self.last_token, self.lengths
        # decode is HBM-bound on the cache stream and every slot's depth
        # is known host-side: attend only the live prefix, bucketed to
        # 256-position steps (few compiled variants; bit-identical
        # tokens — attention past a row's length is masked anyway)
        need = worst + n_steps + 1
        bucket = min(self.max_len, ((need + 255) // 256) * 256)
        attend = bucket if bucket < self.max_len else 0
        seen_in = (self.seen if self.track_seen
                   else jnp.zeros((self.max_batch, 1), jnp.bool_))
        aidx, single = self._adapter_args()
        self.cache, self.last_token, self.lengths, seen_out, toks, lps = (
            self._decode_block(
                self.params, self.cache, self.last_token, self.lengths,
                sub, jnp.float32(max(self.temperature, 1e-6)),
                seen_in,
                jnp.float32(self.repetition_penalty),
                aidx,
                n_steps=n_steps, greedy=self.temperature <= 0.0,
                attend_len=attend, top_k=self.top_k,
                top_p=float(self.top_p), min_p=float(self.min_p),
                penalize=self.track_seen, single=single,
            )
        )
        if self.track_seen:
            self.seen = seen_out
        if self.draft_model is not None:
            # teacher-force the block's inputs ([last, toks[:-1]])
            # through the draft in ONE forward so its cache tracks
            # positions produced outside spec_step
            consumed = jnp.concatenate(
                [last_before[:, None], jnp.swapaxes(toks, 0, 1)[:, :-1]],
                axis=1,
            )
            self.draft_cache = self._draft_catchup(
                self.draft_params, self.draft_cache, consumed,
                lengths_before,
            )
        # kick the device→host copy off NOW: by the time the host comes
        # back to finish(), the transfer rode along with the compute
        for arr in (toks, lps):
            start_async = getattr(arr, "copy_to_host_async", None)
            if start_async is not None:
                try:
                    start_async()
                # purely an overlap hint: any backend quirk degrades to
                # the synchronous device_get in finish()
                except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
                    pass
        self._pending_block = {
            "toks": toks, "lps": lps, "n_steps": n_steps,
            "batch": len(self.slots), "t0": time.perf_counter(),
        }
        get_profiler().event(
            "dispatch", "decode_block",
            n_steps=n_steps, batch=len(self.slots),
        )
        return True

    def decode_block_finish(self) -> Dict[int, List[int]]:
        """Block on the in-flight decode block's tokens and do the host
        bookkeeping (extend per-slot chains, EOS/stop cuts, table
        growth). Returns request id → new tokens ({} when no block is
        in flight)."""
        pending = self._pending_block
        if pending is None:
            return {}
        self._pending_block = None
        # single host round-trip for the block's tokens AND logprobs
        block, block_lp = jax.device_get((pending["toks"],
                                          pending["lps"]))
        self.last_dispatch_landed = time.monotonic()
        get_profiler().event(
            "readback", "decode_block",
            dur_ms=(time.perf_counter() - pending["t0"]) * 1e3,
            n_steps=pending["n_steps"], batch=pending["batch"],
        )
        out: Dict[int, List[int]] = {}
        for slot, req in list(self.slots.items()):
            seq = [int(t) for t in block[:, slot]]
            if self.eos_id is not None and self.eos_id in seq:
                seq = seq[: seq.index(self.eos_id) + 1]
            req.generated.extend(seq)
            req.logprobs.extend(
                float(x) for x in block_lp[: len(seq), slot]
            )
            self.tokens_generated += len(seq)
            out[req.request_id] = seq
            self._maybe_finish(slot)
        self._sync_tables()
        get_tracer().record(
            "engine.decode_block",
            (time.perf_counter() - pending["t0"]) * 1e3,
            n_steps=pending["n_steps"], batch=pending["batch"],
        )
        return out

    # ---- adaptive-k tuning (docs/SERVING.md "Speculative decoding"):
    # the EMA walks the shape-set ladder one rung per crossing, with a
    # hysteresis band so k doesn't thrash on round-to-round noise, and
    # a periodic k=1 probe so a workload that recovered its
    # predictability can climb back out of the k=0 (plain-decode) floor
    SPEC_EMA_BETA = 0.25
    SPEC_EMA_HI = 0.7
    SPEC_EMA_LO = 0.35
    SPEC_PROBE_EVERY = 8

    def _kset_floor(self, k: int) -> int:
        """Largest shape-set member <= k (the set contains 0, so this
        never fails) — every dispatched k must be a compiled shape."""
        out = 0
        for v in self._spec_kset:
            if v <= k:
                out = v
        return out

    def _spec_clamp(self, k: int) -> int:
        """THE k clamp (shared by :meth:`spec_plan_k` and an explicit
        ``spec_step_start(k=...)`` so planner, broadcast, and dispatch
        cannot drift): shrink near the cache end instead of refusing —
        k=0 degrades to a plain (draft-cache-maintaining) step, so a
        slot can always be drained to max_len through this path — then
        floor onto the compiled shape set."""
        worst = max(
            len(r.prompt) + len(r.generated)
            for r in self.slots.values()
        )
        return self._kset_floor(
            max(0, min(k, self.max_len - 2 - worst))
        )

    def spec_plan_k(self, budget_cap: Optional[int] = None) -> int:
        """The k the NEXT spec round will dispatch: the adaptive
        ladder's current rung (acceptance-EMA driven; ``spec_k`` flat
        when ``spec_adaptive`` is off), clamped to the cache headroom
        of the deepest live slot and to the caller's emitted-token cap
        (``budget_cap`` tokens may be emitted at most, so k <=
        budget_cap - 1), floored onto the compiled shape set.

        PURE — no state changes, so scheduler planning (headroom
        charges), the distributed driver's START broadcast, and the
        dispatch itself all see the same k."""
        if self.draft_model is None or not self.slots:
            return 0
        if self.spec_adaptive:
            k = self._spec_kset[self._spec_idx]
            if (k == 0 and len(self._spec_kset) > 1
                    and self._spec_zero_rounds % self.SPEC_PROBE_EVERY
                    == self.SPEC_PROBE_EVERY - 1):
                k = self._spec_kset[1]     # periodic re-measure probe
        else:
            k = self.spec_k
        if budget_cap is not None:
            k = max(0, min(k, budget_cap - 1))
        return self._spec_clamp(k)

    def spec_step(self, k: Optional[int] = None) -> Dict[int, List[int]]:
        """One speculative round for every live slot: draft ``k``
        proposals (one cheap scan), verify with ONE target forward,
        emit the accepted prefix plus one bonus/resampled token —
        between 1 and ``k + 1`` tokens per slot per target pass.
        Greedy engines emit exactly the plain greedy chain
        (bit-identical); at temperature > 0 the acceptance rule is
        standard rejection sampling, so output is
        distribution-identical to plain sampling (losslessness is
        independent of draft quality — only throughput depends on it).

        Rollback costs nothing: rejected positions sit at/beyond each
        slot's new write offset, so the mask never admits them and the
        next round overwrites them — in BOTH caches (the draft's wrong
        entry is exactly its next write position).

        ``k=None`` plans this round's k (:meth:`spec_plan_k` — the
        adaptive ladder). Split form for host/device overlap:
        :meth:`spec_step_start` dispatches draft + verify and starts
        the async readback, :meth:`spec_step_finish` lands the tokens
        and does the host bookkeeping. This method is start + finish."""
        self.spec_step_start(k)
        return self.spec_step_finish()

    def spec_step_start(self, k: Optional[int] = None) -> bool:
        """Dispatch one speculative round WITHOUT blocking on its
        outputs: the draft scan, the fused verify+accept forward, and
        the on-device decode-state advance (``last_token`` /
        ``lengths``) are all enqueued, the accepted-count/token-block
        readback starts asynchronously, and the call returns while the
        device computes. Returns False (no dispatch) on an empty
        batch."""
        if self.draft_model is None:
            raise RuntimeError(
                "spec_step needs an engine built with draft_model="
            )
        self._drain_pending()
        if not self.slots:
            return False
        if self.fault_hook is not None:
            self.fault_hook("spec")
        k = self.spec_plan_k() if k is None else self._spec_clamp(k)
        greedy = self.temperature <= 0.0
        if greedy:
            # greedy consumes no randomness — the RNG stream stays
            # byte-identical to the pre-rejection-sampling engine
            sub = self._rng
        else:
            # ONE split per round, derived keys per consumer: op-stream
            # followers replay the identical split sequence, so the
            # uniform draws (and therefore the accepted counts)
            # converge across replicas
            self._rng, sub = jax.random.split(self._rng)
        draft_rng = jax.random.fold_in(sub, 0)
        verify_rng = jax.random.fold_in(sub, 1)
        temp = jnp.float32(max(self.temperature, 1e-6))
        # the draft scans k+1 steps: step j consumes [last, d0..d_{k-1}]
        # so on FULL acceptance (new write position = lens+k+1) every
        # admitted draft-cache position is really written — a k-step
        # scan would leave d_{k-1}'s position as a permanent zero-hole
        self.draft_cache, d_all, q_all = self._spec_draft(
            self.draft_params, self.draft_cache, self.last_token,
            self.lengths, draft_rng, temp, k=k + 1, greedy=greedy,
            top_k=self.top_k, top_p=float(self.top_p),
            min_p=float(self.min_p),
        )
        d = d_all[:, :k]
        q = q_all if greedy else q_all[:, :k]
        inputs = jnp.concatenate([self.last_token[:, None], d], axis=1)
        self.cache, accepted, out, lps, final = self._spec_verify(
            self.params, self.cache, inputs, self.lengths, d, q,
            verify_rng, temp, greedy=greedy, top_k=self.top_k,
            top_p=float(self.top_p), min_p=float(self.min_p),
        )
        # decode state advances ON DEVICE — the host sees nothing until
        # finish(), so scheduler host work overlaps the whole chain
        self.last_token = final
        self.lengths = self.lengths + accepted + 1
        # kick the device→host copy off NOW: by the time the host comes
        # back to finish(), the transfer rode along with the compute
        for arr in (accepted, out, lps):
            start_async = getattr(arr, "copy_to_host_async", None)
            if start_async is not None:
                try:
                    start_async()
                # purely an overlap hint: any backend quirk degrades to
                # the synchronous device_get in finish()
                except Exception:  # noqa: BLE001  # slicelint: disable=broad-except
                    pass
        self._pending_spec = {
            "accepted": accepted, "out": out, "lps": lps, "k": k,
            "batch": len(self.slots), "t0": time.perf_counter(),
        }
        get_profiler().event(
            "dispatch", "spec_round", k=k, batch=len(self.slots),
        )
        return True

    def spec_step_finish(self) -> Dict[int, List[int]]:
        """Block on the in-flight spec round's outputs and do the host
        bookkeeping: extend per-slot chains (EOS/stop cuts included),
        update the acceptance EMA + adaptive-k ladder, grow block
        tables. Returns request id → new tokens ({} when no round is
        in flight)."""
        pending = self._pending_spec
        if pending is None:
            return {}
        self._pending_spec = None
        a_h, out_h, lp_h = jax.device_get(
            (pending["accepted"], pending["out"], pending["lps"])
        )
        self.last_dispatch_landed = time.monotonic()
        get_profiler().event(
            "readback", "spec_round",
            dur_ms=(time.perf_counter() - pending["t0"]) * 1e3,
            k=pending["k"], batch=pending["batch"],
        )
        k = pending["k"]
        out: Dict[int, List[int]] = {}
        accepted_sum = 0
        for slot, req in list(self.slots.items()):
            n = int(a_h[slot])
            accepted_sum += n
            seq = [int(x) for x in out_h[slot, : n + 1]]
            if self.eos_id is not None and self.eos_id in seq:
                seq = seq[: seq.index(self.eos_id) + 1]
            req.generated.extend(seq)
            req.logprobs.extend(
                float(x) for x in lp_h[slot, : len(seq)]
            )
            self.tokens_generated += len(seq)
            out[req.request_id] = seq
            self._maybe_finish(slot)
        self.spec_rounds += 1
        if k > 0:
            proposed = k * pending["batch"]
            self.spec_proposed += proposed
            self.spec_accepted += accepted_sum
            rate = accepted_sum / proposed
            self._spec_rate_samples.append(rate)
            self._spec_zero_rounds = 0
            if self.spec_adaptive:
                self.spec_accept_ema = (
                    (1.0 - self.SPEC_EMA_BETA) * self.spec_accept_ema
                    + self.SPEC_EMA_BETA * rate
                )
                if (self.spec_accept_ema >= self.SPEC_EMA_HI
                        and self._spec_idx < len(self._spec_kset) - 1):
                    self._spec_idx += 1
                elif (self.spec_accept_ema <= self.SPEC_EMA_LO
                        and self._spec_idx > 0):
                    self._spec_idx -= 1
        else:
            self._spec_zero_rounds += 1
        self._sync_tables()
        get_tracer().record(
            "engine.spec_round",
            (time.perf_counter() - pending["t0"]) * 1e3,
            k=k, batch=pending["batch"], accepted=accepted_sum,
        )
        return out

    def spec_stats(self) -> dict:
        """The speculative-decoding observability block (/v1/stats
        ``spec``): shape-set/ladder gauges plus the rounds/proposed/
        accepted ledger the scheduler delta-exports."""
        if self.draft_model is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "k": self.spec_plan_k() if self.slots
            else (self._spec_kset[self._spec_idx] if self.spec_adaptive
                  else self.spec_k),
            "k_max": self.spec_k,
            "k_set": list(self._spec_kset),
            "adaptive": self.spec_adaptive,
            "acceptance_ema": round(self.spec_accept_ema, 4),
            "rounds": self.spec_rounds,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
        }

    def warm_spec_programs(self) -> None:
        """Compile the FULL draft/verify shape set NOW — every k the
        adaptive ladder (or the cache-end clamp) can dispatch, for the
        engine's current sampling mode — plus the draft prefill
        program, against the live caches with zero admissions. Call
        once before taking traffic (the serve CLI does, right next to
        :meth:`warm_prefill_buckets`; the bench does per arm) so no
        round pays a compile mid-measurement: PR 11 measured a single
        cold mid-run compile polluting a seconds-long TTFT p95 tail.
        The dummy dispatches scribble masked positions of empty slots'
        stripes — harmless while nothing is live (admission prefill
        overwrites everything it attends). No-op without a draft."""
        if self.draft_model is None:
            return
        if self.slots:
            raise RuntimeError(
                "warm_spec_programs must run before any admission "
                "(it scribbles on empty slots' masked stripes)"
            )
        greedy = self.temperature <= 0.0
        rng = jax.random.fold_in(jax.random.key(0), 0)
        if self._replicated is not None:
            rng = jax.device_put(rng, self._replicated)
        temp = jnp.float32(max(self.temperature, 1e-6))
        P = self.prefill_len
        self.draft_cache = self._draft_prefill(
            self.draft_params, self.draft_cache,
            jnp.zeros((1, P), jnp.int32), 0, 0,
        )
        for k in self._spec_kset:
            self.draft_cache, d_all, q_all = self._spec_draft(
                self.draft_params, self.draft_cache, self.last_token,
                self.lengths, rng, temp, k=k + 1, greedy=greedy,
                top_k=self.top_k, top_p=float(self.top_p),
                min_p=float(self.min_p),
            )
            d = d_all[:, :k]
            q = q_all if greedy else q_all[:, :k]
            inputs = jnp.concatenate(
                [self.last_token[:, None], d], axis=1
            )
            self.cache, *_ = self._spec_verify(
                self.params, self.cache, inputs, self.lengths, d, q,
                rng, temp, greedy=greedy, top_k=self.top_k,
                top_p=float(self.top_p), min_p=float(self.min_p),
            )

    @staticmethod
    def _find_stop(generated: List[int], stops: List[List[int]],
                   scanned: int = 0) -> int:
        """Start index of the earliest stop-sequence match in
        ``generated``, or -1. Resumes a stop-window before ``scanned``
        (positions the caller already cleared) rather than from zero, so
        repeated per-block checks stay O(new tokens) while matches split
        across block boundaries are still found."""
        best = -1
        for seq in stops:
            n = len(seq)
            for i in range(max(0, scanned - n + 1),
                           len(generated) - n + 1):
                if generated[i:i + n] == seq:
                    if best < 0 or i < best:
                        best = i
                    break
        return best

    def _maybe_finish(self, slot: int) -> None:
        req = self.slots[slot]
        total = len(req.prompt) + len(req.generated)
        reason = ""
        if req.stop:
            cut = self._find_stop(req.generated, req.stop,
                                  req.stop_scanned)
            if cut >= 0:
                # exclude the stop sequence itself (OpenAI semantics)
                req.generated = req.generated[:cut]
                req.logprobs = req.logprobs[:cut]
                reason = "stop"
            else:
                req.stop_scanned = len(req.generated)
        if not reason:
            if self.eos_id is not None and req.generated[-1] == self.eos_id:
                reason = "eos"
            elif total >= self.max_len - 1:
                reason = "max_len"
        if reason:
            self.finished.append(
                GenerationResult(
                    req.request_id, req.prompt, req.generated, reason,
                    logprobs=req.logprobs,
                )
            )
            del self.slots[slot]
            self._release_table(req.request_id)
            # completion feeds the radix prefix cache (after the
            # release: the freed blocks are the room the insert wants)
            self._radix_insert(slot, req)

    def generate(
        self, prompts: List[List[int]], max_new_tokens: int,
        block_size: int = 32, stop=None,
    ) -> List[GenerationResult]:
        """Batch convenience: run all prompts to completion (continuous
        batching: new prompts are admitted as slots free up).

        Decodes in on-device blocks (:meth:`decode_block`) of up to
        ``block_size`` steps — capped at the smallest remaining budget
        among this call's requests so no request overshoots
        ``max_new_tokens``, and at the cache headroom."""
        pending = list(enumerate(prompts))
        want: Dict[int, int] = {}
        results: Dict[int, GenerationResult] = {}
        budget: Dict[int, int] = {}
        while True:
            while pending and self.free_slots():
                idx, p = pending.pop(0)
                rid = self.add_request(p, stop=stop)
                want[rid] = idx
                budget[rid] = max_new_tokens
            # enforce the per-request budget BEFORE decoding (add_request
            # already produced one token, so max_new_tokens=1 requests
            # are done on admission) — only for requests admitted by THIS
            # call; slots created via add_request()/throughput() before
            # generate() keep running under their own rules
            for slot, req in list(self.slots.items()):
                if (
                    req.request_id in budget
                    and len(req.generated) >= budget[req.request_id]
                ):
                    self.finish_slot(
                        slot, n_keep=budget[req.request_id]
                    )
            # harvest only our own finished entries; leave results that
            # belong to requests outside this call for their owners
            remaining: List[GenerationResult] = []
            for r in self.finished:
                if r.request_id in want:
                    results[want.pop(r.request_id)] = r
                else:
                    remaining.append(r)
            self.finished = remaining
            if not pending and not any(
                req.request_id in budget for req in self.slots.values()
            ):
                break  # foreign slots still live; ours are all done
            if self.slots:
                owned = [
                    r for r in self.slots.values()
                    if r.request_id in budget
                ]
                n = block_size
                if owned:
                    # at-budget slots were just removed: remaining >= 1
                    n = min(n, min(
                        budget[r.request_id] - len(r.generated)
                        for r in owned
                    ))
                worst = max(
                    len(r.prompt) + len(r.generated)
                    for r in self.slots.values()
                )
                n = min(n, self.max_len - 2 - worst)
                if n >= 1:
                    self.decode_block(n)
                else:
                    self.step()  # a slot at capacity: finish it one
                    #              step at a time (_maybe_finish max_len)
        return [results[i] for i in sorted(results)]

    def spec_throughput(
        self, rounds: int = 32, batch: Optional[int] = None,
        overhead_seconds: float = 0.0, detail: bool = False,
    ):
        """(tokens/sec, accepted tokens/round) over ``rounds``
        speculative rounds at the given concurrency — the spec-decode
        counterpart of :meth:`throughput`, sharing its admit + warm +
        refill methodology. Slots that drain at ``max_len`` mid-run are
        refilled every round, so the number is steady-state serving
        throughput (admission cost included, as in real traffic), never
        a spin on an empty engine. ``overhead_seconds`` is the per-round
        host readback (spec_step reads back every round, unlike the
        block-decode scan)."""
        if self.draft_model is None:
            raise RuntimeError(
                "spec_throughput needs an engine built with draft_model="
            )
        batch = batch or self.max_batch
        for _ in range(min(batch, self.free_slots())):
            self.add_request([1, 2, 3])
        self.spec_step()                              # compile + warm
        produced = slot_rounds = 0
        t0 = time.perf_counter()
        for _ in range(rounds):
            for _ in range(min(batch, self.max_batch) - len(self.slots)):
                self.add_request([1, 2, 3])           # refill drained
            slot_rounds += len(self.slots)
            out = self.spec_step()
            produced += sum(len(v) for v in out.values())
        wall = time.perf_counter() - t0
        dt = max(wall - overhead_seconds * rounds, 1e-6)
        if detail:
            # both sides of the RTT bracket from ONE measurement: raw
            # (no subtraction — what a tunnel-remote client observes)
            # and corrected (what the chip sustains); running twice
            # would double a tunnel-bound phase AND compare runs with
            # different noise
            return {
                "tokens_per_sec": produced / dt,
                "tokens_per_sec_raw": produced / max(wall, 1e-6),
                "tokens_per_round": produced / max(1, slot_rounds),
                "produced": produced,
                "wall_seconds": round(wall, 3),
            }
        return produced / dt, produced / max(1, slot_rounds)

    def throughput(
        self, n_steps: int = 50, batch: Optional[int] = None,
        overhead_seconds: float = 0.0,
    ) -> float:
        """Decode tokens/sec at the given concurrency (BASELINE secondary
        metric: tokens/sec/chip — divide by the slice's chip count).

        Measures the on-device block-decode path: one compiled scan of
        ``n_steps`` steps, one readback. ``overhead_seconds`` (e.g. a
        measured host↔device round-trip, significant over a tunnel) is
        subtracted from the wall time."""
        batch = batch or self.max_batch
        for _ in range(min(batch, self.free_slots())):
            self.add_request([1, 2, 3])
        # two blocks (warm + timed) must both fit the cache: clamp the
        # block size to half the headroom of the deepest slot
        worst = max(
            (len(r.prompt) + len(r.generated)
             for r in self.slots.values()),
            default=0,
        )
        n = min(n_steps, max(1, (self.max_len - 2 - worst) // 2))
        self.decode_block(n)                          # compile + warm
        # refill slots the warm-up finished (eos / max_len) so the timed
        # block never measures an empty batch
        for _ in range(min(batch, self.free_slots())):
            self.add_request([1, 2, 3])
        t0 = time.perf_counter()
        out = self.decode_block(n)
        dt = time.perf_counter() - t0 - overhead_seconds
        done = sum(len(seq) for seq in out.values())
        return done / dt if dt > 0 else 0.0
