"""Multi-host serving: the driver/follower op-stream.

Multi-process JAX is SPMD — every process of a DCN-spanning mesh must
execute the SAME jitted calls in the SAME order, or the collectives
deadlock. An HTTP server takes requests on one host only, so serving a
multi-host slice needs exactly one new mechanism: worker 0 (the
**driver**) decides the op sequence and broadcasts it; workers 1..N-1
(**followers**) replay it verbatim on their local :class:`ServingEngine`
replica. Engines are deterministic given the same op sequence (same
seed, same host bookkeeping), so every process issues identical compiled
programs and the tensor-parallel collectives line up. Results are read
on the driver only — the engine's token outputs are replicated across
the mesh (``ServingEngine`` forces replicated out-shardings on
multi-process meshes), so worker 0 fully addresses them.

This is the TPU-native analog of vLLM's driver/worker RPC split, with
the op-log as the entire protocol: newline-delimited JSON over one TCP
connection per follower, ops applied strictly in order.

The radix prefix cache needs NO ops of its own: every tree mutation is
engine-internal and deterministic — matches/touches/locks happen
inside the admission ops, insertion inside the decode/finish ops that
complete a request, LRU eviction inside whichever op needed the blocks
— and the LRU clock is logical (never wall time), so replaying the op
stream converges every follower on the identical tree (structure,
block accounting, eviction order). ``ServingEngine.radix_stats()`` is
the convergence observable the tests compare.

Wire format (one JSON object per line)::

    {"op": "add_request", "prompt": [...], "stop": [[...]], "n": 1,
     "adapter": 0}
    {"op": "add_requests", "reqs": [{"prompt": [...], "n": 1,
     "stop": [[...]], "adapter": 0}, ...]}
    {"op": "step"} | {"op": "decode_block", "n": 8}
    {"op": "spec_step", "k": 4}
    {"op": "register_prefix", "tokens": [...]}
    {"op": "drop_prefix", "tokens": [...]}
    {"op": "finish_slot", "slot": 0, "n_keep": 5, "reason": "..."}
    {"op": "evict_slot", "slot": 0}
    {"op": "preempt_slot", "slot": 0}
    {"op": "resume_request", "rid": 7}
    {"op": "drop_parked", "rid": 7}
    {"op": "import_session", "blob": {...session wire format...}}
    {"op": "shutdown"}

Usage — driver (worker 0)::

    eng = ServingEngine(model, mesh=global_mesh, ...)
    deng = DistributedEngine(eng, n_followers=topo.num_workers - 1,
                             port=oplog_port)   # blocks for followers
    deng.generate(prompts, max_new_tokens=64)   # or ApiServer(deng)

followers (workers 1..N-1)::

    eng = ServingEngine(model, mesh=global_mesh, ...)  # identical args
    run_follower(eng, driver_host, oplog_port)         # blocks

``ApiServer(deng)`` works unchanged: the scheduler only mutates the
engine through the public ops this wrapper broadcasts
(``add_request`` / ``decode_block`` / ``spec_step`` / ``finish_slot`` /
``evict_slot`` / prefix ops).
"""

from __future__ import annotations

import json
import logging
import socket
import time
from typing import List, Optional

from instaslice_tpu.serving.engine import AdmissionRequest, ServingEngine

log = logging.getLogger("instaslice_tpu.serving.distributed")

#: follower handshake marker (first line on connect)
HELLO_MAGIC = "tpuslice-oplog-v1"


def _recv_line(sock: socket.socket, limit: int = 4096) -> bytes:
    """Read up to the first newline (handshake use; tiny payload)."""
    buf = b""
    while b"\n" not in buf:
        chunk = sock.recv(1024)
        if not chunk or len(buf) > limit:
            raise OSError("connection closed during handshake")
        buf += chunk
    return buf.split(b"\n", 1)[0]


class DistributedEngine:
    """Worker-0 wrapper: broadcast each op to every follower, then
    apply it locally. Reads (``slots``, ``finished``, counters…)
    delegate to the local engine untouched."""

    def __init__(self, engine: ServingEngine, n_followers: int,
                 port: int, bind_host: str = "0.0.0.0",
                 accept_timeout: float = 120.0) -> None:
        self.engine = engine
        self._conns: List[tuple] = []       # (socket, peer-addr string)
        if n_followers:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((bind_host, port))
            srv.listen(n_followers + 4)
            deadline = time.monotonic() + accept_timeout
            while len(self._conns) < n_followers:
                srv.settimeout(max(deadline - time.monotonic(), 0.001))
                conn, addr = srv.accept()
                # one-line hello gates the op stream: a stray connector
                # (port scan, prober) must not consume a follower slot
                # or receive the broadcast (it carries prompt tokens)
                try:
                    conn.settimeout(10.0)
                    hello = json.loads(_recv_line(conn))
                    if hello.get("hello") != HELLO_MAGIC:
                        raise ValueError("bad hello")
                except (ValueError, OSError):
                    log.warning("rejecting non-follower connection "
                                "from %s", addr)
                    conn.close()
                    continue
                conn.settimeout(None)
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns.append((conn, f"{addr[0]}:{addr[1]}"))
            srv.close()

    # ------------------------------------------------------------- plumbing

    def __setattr__(self, name, value):
        # generate() (run unbound over this wrapper) reassigns engine
        # attributes like ``finished`` — route them to the engine so the
        # wrapper never shadows live state
        if name in ("engine", "_conns"):
            object.__setattr__(self, name, value)
        else:
            setattr(self.engine, name, value)

    def _bcast(self, op: dict) -> None:
        """Send to every live follower. A dead follower is dropped with
        a loud log instead of raising into the scheduler thread — on
        real multi-host its devices are gone from the mesh anyway (a
        jax.distributed failure), and the local server must keep
        serving/failing requests rather than silently dying."""
        from instaslice_tpu.faults.netchaos import get_nemesis

        line = (json.dumps(op) + "\n").encode()
        dead = []
        nemesis = get_nemesis()
        for pair in self._conns:
            conn, addr = pair
            try:
                if nemesis is not None:
                    # PartitionError is an OSError: a partitioned
                    # follower takes the same drop path as a dead one
                    nemesis.before_request("opstream", f"follower:{addr}")
                    nemesis.throttle_sleep(
                        "opstream", f"follower:{addr}", len(line))
                conn.sendall(line)
            except OSError as e:
                # addr captured at accept time: a reset socket raises
                # ENOTCONN from getpeername(), which would escape this
                # handler and kill the scheduler thread
                log.error("dropping dead follower %s: %s", addr, e)
                dead.append(pair)
        for pair in dead:
            self._conns.remove(pair)
            try:
                pair[0].close()
            except OSError:
                pass

    def __getattr__(self, name):
        # reads and non-broadcast helpers fall through to the engine
        return getattr(self.engine, name)

    # ------------------------------------------------------------- the ops

    def add_request(self, prompt: List[int], stop=None,
                    adapter: int = 0) -> int:
        return self.add_request_n(prompt, 1, stop=stop,
                                  adapter=adapter)[0]

    def add_request_n(self, prompt: List[int], n: int,
                      stop=None, adapter: int = 0) -> List[int]:
        # host-side validation BEFORE the broadcast: a rejected request
        # must not enter the op stream at all. (Followers additionally
        # swallow deterministic validation errors, so even an op that
        # slips through fails identically on every replica.)
        stop = ServingEngine._normalize_stop(stop)
        self.engine._check_prompt_fits(prompt)
        self.engine._check_capacity(n)
        # adapter rides the op stream: a follower replaying through the
        # base model while the driver used an adapter would silently
        # diverge the replicas
        self._bcast({"op": "add_request", "prompt": list(prompt),
                     "stop": stop, "n": n, "adapter": adapter})
        return self.engine.add_request_n(prompt, n, stop=stop,
                                         adapter=adapter)

    def add_requests(self, reqs):
        """Burst admission rides the op stream as ONE op: followers
        replay the identical batched prefill dispatches (same bucketed
        shapes), so the compiled-program sets stay aligned."""
        reqs = [r if isinstance(r, AdmissionRequest)
                else AdmissionRequest(**r) for r in reqs]
        norm = []
        for r in reqs:
            stop = ServingEngine._normalize_stop(r.stop)
            self.engine._check_prompt_fits(r.prompt)
            norm.append(AdmissionRequest(list(r.prompt), r.n, stop,
                                         r.adapter))
        self.engine._check_capacity(sum(r.n for r in norm))
        self._bcast({"op": "add_requests", "reqs": [
            {"prompt": r.prompt, "n": r.n, "stop": r.stop,
             "adapter": r.adapter} for r in norm
        ]})
        return self.engine.add_requests(norm)

    def step(self):
        self._bcast({"op": "step"})
        return self.engine.step()

    def decode_block(self, n_steps: int):
        self._bcast({"op": "decode_block", "n": n_steps})
        return self.engine.decode_block(n_steps)

    def decode_block_start(self, n_steps: int):
        """The overlap seam over the op stream: the BROADCAST happens
        at start (followers dispatch their block concurrently with the
        driver's — that is the point); finish is driver-local (the
        followers' replayed decode_block does its own readback)."""
        self._bcast({"op": "decode_block", "n": n_steps})
        return self.engine.decode_block_start(n_steps)

    def decode_block_finish(self):
        return self.engine.decode_block_finish()

    def spec_step(self, k=None):
        if k is None:
            k = self.engine.spec_plan_k()
        self._bcast({"op": "spec_step", "k": k})
        return self.engine.spec_step(k=k)

    def spec_step_start(self, k=None):
        """The spec overlap seam over the op stream, exactly like
        decode_block_start: the broadcast happens at START — with the
        driver's PLANNED k pinned into the op, so followers dispatch
        the identical draft/verify shapes even if their adaptive-EMA
        state ever drifted — and followers compute concurrently with
        the driver; finish is driver-local."""
        if k is None:
            k = self.engine.spec_plan_k()
        self._bcast({"op": "spec_step", "k": k})
        return self.engine.spec_step_start(k=k)

    def spec_step_finish(self):
        return self.engine.spec_step_finish()

    def register_prefix(self, prefix: List[int]) -> None:
        if tuple(prefix) not in self.engine.prefixes:
            self.engine._validate_prefix(prefix)   # before the broadcast
        self._bcast({"op": "register_prefix", "tokens": list(prefix)})
        self.engine.register_prefix(prefix)

    def drop_prefix(self, prefix: List[int]) -> bool:
        self._bcast({"op": "drop_prefix", "tokens": list(prefix)})
        return self.engine.drop_prefix(prefix)

    def finish_slot(self, slot: int, n_keep: Optional[int] = None,
                    reason: str = "max_new_tokens") -> None:
        self._bcast({"op": "finish_slot", "slot": slot,
                     "n_keep": n_keep, "reason": reason})
        self.engine.finish_slot(slot, n_keep=n_keep, reason=reason)

    def evict_slot(self, slot: int) -> None:
        self._bcast({"op": "evict_slot", "slot": slot})
        self.engine.evict_slot(slot)

    def preempt_slot(self, slot: int) -> int:
        # preemption/resume change slot occupancy AND dispatch stripe
        # read/write jits, so they are broadcast surface exactly like
        # finish_slot; parked state replays deterministically per host
        self._bcast({"op": "preempt_slot", "slot": slot})
        return self.engine.preempt_slot(slot)

    def resume_request(self, rid: int) -> int:
        if rid not in self.engine.parked:
            raise ValueError(f"request {rid} is not parked")
        self._bcast({"op": "resume_request", "rid": rid})
        return self.engine.resume_request(rid)

    def drop_parked(self, rid: int) -> bool:
        self._bcast({"op": "drop_parked", "rid": rid})
        return self.engine.drop_parked(rid)

    def import_session(self, blob: dict) -> int:
        """Inbound live migration rides the op stream: every replica
        materializes the identical parked state (and adopts the blob's
        RNG key), so the later resume_request replays aligned. The blob
        is validated BEFORE the broadcast — a rejected session must
        never enter the op stream. export_session needs no op: it is a
        pure read of parked state (and is refused on multi-process
        meshes — see the engine)."""
        self.engine._validate_session_blob(blob)
        self._bcast({"op": "import_session", "blob": blob})
        return self.engine.import_session(blob)

    def generate(self, prompts, max_new_tokens, block_size: int = 32,
                 stop=None):
        # ServingEngine.generate drives everything through the public
        # ops above, so running it unbound with this wrapper as `self`
        # broadcasts every device-touching step (duck typing is the
        # point: the wrapper IS engine-shaped)
        return ServingEngine.generate(
            self, prompts, max_new_tokens, block_size=block_size,
            stop=stop,
        )

    def shutdown(self) -> None:
        """Release the followers (they return from run_follower)."""
        self._bcast({"op": "shutdown"})
        for conn, _addr in self._conns:
            conn.close()
        self._conns = []


def run_follower(engine: ServingEngine, driver_host: str, port: int,
                 connect_timeout: float = 120.0) -> int:
    """Replay the driver's op stream on the local engine replica until
    shutdown/EOF; returns the number of ops applied.

    Every op triggers the same jitted calls the driver issues, which is
    what keeps the multi-process collectives aligned. Results are
    intentionally discarded — the driver owns delivery."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    deadline = time.monotonic() + connect_timeout
    while True:
        try:
            sock.connect((driver_host, port))
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            # driver not listening yet: deadline-bounded startup poll
            # (no stop event exists before the stream is established)
            time.sleep(0.2)  # slicelint: disable=sleep-in-loop
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.sendall((json.dumps({"hello": HELLO_MAGIC}) + "\n").encode())
    applied = 0
    buf = b""
    try:
        while True:
            nl = buf.find(b"\n")
            if nl < 0:
                chunk = sock.recv(65536)
                if not chunk:
                    return applied                    # driver went away
                buf += chunk
                continue
            line, buf = buf[:nl], buf[nl + 1:]
            op = json.loads(line)
            kind = op["op"]
            if kind == "shutdown":
                return applied
            if kind not in ("add_request", "add_requests", "step",
                            "decode_block",
                            "spec_step", "register_prefix",
                            "drop_prefix", "finish_slot", "evict_slot",
                            "preempt_slot", "resume_request",
                            "drop_parked", "import_session"):
                # a protocol mismatch is NOT deterministic-skip
                # territory: replicas are about to diverge — die loudly
                raise RuntimeError(f"unknown op {kind!r} in op stream")
            try:
                if kind == "add_request":
                    engine.add_request_n(op["prompt"], op.get("n", 1),
                                         stop=op["stop"],
                                         adapter=op.get("adapter", 0))
                elif kind == "add_requests":
                    engine.add_requests([
                        AdmissionRequest(r["prompt"], r.get("n", 1),
                                         r.get("stop"),
                                         r.get("adapter", 0))
                        for r in op["reqs"]
                    ])
                elif kind == "step":
                    engine.step()
                elif kind == "decode_block":
                    engine.decode_block(op["n"])
                elif kind == "spec_step":
                    # the driver's planned k rides the op. A missing k
                    # (a pre-r12 driver) makes the follower plan its
                    # own — best effort only: a mixed-version mesh is
                    # NOT a supported deployment (driver and followers
                    # ship in one pod template and restart together),
                    # and an old driver's un-floored k need not match
                    # the new shape set
                    engine.spec_step(k=op.get("k"))
                elif kind == "register_prefix":
                    engine.register_prefix(op["tokens"])
                elif kind == "drop_prefix":
                    engine.drop_prefix(op["tokens"])
                elif kind == "finish_slot":
                    engine.finish_slot(op["slot"], n_keep=op["n_keep"],
                                       reason=op["reason"])
                elif kind == "evict_slot":
                    engine.evict_slot(op["slot"])
                elif kind == "preempt_slot":
                    engine.preempt_slot(op["slot"])
                elif kind == "resume_request":
                    engine.resume_request(op["rid"])
                elif kind == "drop_parked":
                    engine.drop_parked(op["rid"])
                elif kind == "import_session":
                    engine.import_session(op["blob"])
            except (ValueError, KeyError, RuntimeError) as e:
                # deterministic host-side validation failure: the
                # driver hit (or pre-screened) the exact same error, so
                # replica state stays aligned by SKIPPING it here too.
                # RuntimeError SUBCLASSES (jaxlib's XlaRuntimeError,
                # device OOM…) are real per-host failures: skipping
                # would silently drop a jitted call the driver executed
                # and deadlock its collectives — die loudly instead so
                # the pod restarts.
                if isinstance(e, RuntimeError) and \
                        type(e) is not RuntimeError:
                    raise
                log.warning("skipping op %s: %s", kind, e)
            # results are the driver's business: drain the follower's
            # finished list so it can't grow without bound
            engine.finished.clear()
            applied += 1
    finally:
        sock.close()
