"""Serving engine: continuous-batched autoregressive decoding on a slice.

The reference's serving story is a sample YAML that points vLLM at the
granted MIG slice (``/root/reference/samples/vllm_dep.yaml``, SURVEY.md
§1); the TPU build ships a real engine because the BASELINE secondary
metric (tokens/sec/chip) needs a measurable decode path on the granted
mesh.
"""

from instaslice_tpu.serving.engine import (
    AdmissionRequest,
    GenerationResult,
    ServingEngine,
)
from instaslice_tpu.serving.kvcache import KVBlockPool

__all__ = ["AdmissionRequest", "ServingEngine", "GenerationResult",
           "KVBlockPool"]
