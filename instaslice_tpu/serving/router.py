"""Fleet serving tier: prefix/SLO-aware HTTP router over engine replicas.

One ``ApiServer`` owns one engine, so adding a slice added zero serving
capacity to any endpoint — this front-end turns N independent replicas
into ONE ``/v1/*`` endpoint whose aggregate tok/s scales with replica
count while prefix-affine routing *improves* TTFT (a request routed to
the replica whose radix cache already holds its prefix skips the
prefill every other replica would pay). The spirit is the
multi-replica/disaggregated serving layouts PAPERS.md surveys
(ParvaGPU's right-sized spatial shares; Flex-MIG's one-job-many-slices
composition), built on pieces the serving plane already exports:

- **Feedback, not configuration**: replicas are polled over
  ``/v1/stats`` (queue depth, free KV blocks, hashed radix hot-prefix
  digest, spec acceptance, ``replica_id`` + ``uptime_seconds``). A
  restarted replica (new nonce / clock reset) is detected and its
  affinity state discarded — its radix cache and sessions died with it.
- **Routing policy, in order** (docs/SERVING.md "Fleet router &
  session migration"):

  1. *session affinity* — ``X-Session-Id`` (or ``"session"`` field)
     pins a multi-turn conversation to the replica whose radix cache
     holds its history;
  2. *prefix-cache affinity* — the prompt's granule-hash chain is
     walked against each replica's advertised digest (a router-side
     shadow index; hashes only, tokens never leave a replica), longest
     match wins, ties break toward least load;
  3. *least-loaded* — queue depth + batch occupancy weighted by KV
     pressure, with latency-class tenants penalizing queues harder.

- **Per-replica circuit breaking** reuses the kube transport's
  :class:`~instaslice_tpu.kube.real.CircuitBreaker` (same
  threshold/half-open-probe semantics); a broken replica drops out of
  routing until its cooldown probe.
- **Live KV session migration** makes the fleet elastic without perf
  cliffs: removing a replica drains it with ``{"migrate": true}`` —
  every in-flight session's terminal response carries its exported KV
  stripe (``text_completion.migration``), and the proxy thread already
  holding both connections imports it into a peer
  (``/v1/sessions/import`` → ``{"resume": rid}``) and splices the
  resumed stream, so the client sees one continuous completion: no
  503, no re-prefill, token-identical. The same primitive rebalances a
  hot replica mid-stream (``POST /v1/rebalance``).

Run via ``tpuslice-router --replica http://host:8000 ...`` or embed
:class:`Router` (the bench does). The router is stateless beyond
affinity maps — killing it loses no session state (replicas own the
KV), which is the property that lets it front "millions of users"
without itself becoming the thing that needs migrating.
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from instaslice_tpu.api.constants import (
    REASON_REPLICA_EJECTED,
    REASON_REPLICA_READMITTED,
)
from instaslice_tpu.faults.netchaos import get_nemesis
from instaslice_tpu.kube.real import CircuitBreaker, CircuitOpen
from instaslice_tpu.obs.journal import debug_events_payload, get_journal
from instaslice_tpu.obs.profiler import (
    debug_profile_payload,
    get_profiler,
)
from instaslice_tpu.serving.kvcache import granule_hash
from instaslice_tpu.utils.guards import guarded_by, unguarded
from instaslice_tpu.utils.lockcheck import debug_locks_payload, named_lock
from instaslice_tpu.utils.trace import TRACE_ID_SAFE, \
    debug_trace_payload, get_tracer, new_trace_id

log = logging.getLogger("instaslice_tpu.serving.router")

#: transport failures that count against a replica's breaker
_TRANSPORT_EXC = (urllib.error.URLError, ConnectionError, TimeoutError,
                  OSError)


def _retry_after_seconds(headers) -> Optional[float]:
    """Parse a Retry-After header (delta-seconds form, like
    kube/real.py honors)."""
    raw = headers.get("Retry-After") if headers is not None else None
    if not raw:
        return None
    try:
        return max(0.0, float(raw))
    except ValueError:
        return None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n % 2:
        return s[n // 2]
    return 0.5 * (s[n // 2 - 1] + s[n // 2])


def want_hashes(prompt: List[int], granule: int) -> List[str]:
    """The prompt's whole-granule hash chain at one granule size —
    what :meth:`Replica.prefix_match` walks the advertised chains
    against."""
    if granule <= 0 or not prompt:
        return []
    n = len(prompt) // granule
    return [
        granule_hash(tuple(prompt[i * granule:(i + 1) * granule]))
        for i in range(n)
    ]


class NoReplica(RuntimeError):
    """No routable replica: every replica is dead, draining, or
    circuit-broken — the router's 503."""


class Replica:
    """One engine replica as the router sees it: last polled stats,
    the shadow prefix index built from its advertised radix digest,
    and its circuit breaker."""

    #: smoothing factor for the poll-latency EWMA (mean + variance)
    EWMA_ALPHA = 0.3

    def __init__(self, url: str, breaker_threshold: int = 3,
                 breaker_cooldown: float = 2.0) -> None:
        self.url = url.rstrip("/")
        self.breaker = CircuitBreaker(breaker_threshold,
                                      breaker_cooldown, name=self.url)
        self.stats: dict = {}
        self.replica_id = ""
        self.uptime = -1.0
        self.last_poll = 0.0          # monotonic; 0 = never
        self.draining = False         # router-side: no NEW routes
        #: gray-failure ejection (docs/RECOVERY.md "Partitions & gray
        #: failures"): latency EWMA past threshold with a 100% success
        #: rate — unroutable like draining, but the router keeps
        #: polling and re-admits when the EWMA recovers
        self.ejected = False
        # poll-latency EWMA (mean + variance → p95 estimate): the gray-
        # failure signal the breaker cannot see (it only counts errors)
        self.lat_mean = 0.0
        self.lat_var = 0.0
        self.lat_samples = 0
        # stats-poll failure backoff (capped decorrelated jitter,
        # Retry-After honored — satellite of the nemesis PR: fixed-
        # interval re-polls stampede a just-healed replica)
        self.poll_backoff = 0.0
        self.poll_next = 0.0          # monotonic; 0 = poll freely
        self.retry_after_hint: Optional[float] = None
        #: shadow prefix index: advertised hot paths as granule-hash
        #: chains, plus the granule size they were cut at
        self.granule = 0
        self.chains: List[List[str]] = []

    def alive(self, now: float, stale_after: float) -> bool:
        """Routable: polled recently, not circuit-broken, not marked
        draining or gray-ejected by the router."""
        return (bool(self.stats) and not self.draining
                and not self.ejected
                and not self.breaker.is_open()
                and now - self.last_poll <= stale_after)

    def observe_latency(self, dt: float) -> None:
        """Fold one successful round-trip latency into the EWMA."""
        if self.lat_samples == 0:
            self.lat_mean = dt
            self.lat_var = 0.0
        else:
            a = self.EWMA_ALPHA
            d = dt - self.lat_mean
            self.lat_mean += a * d
            # exponentially weighted variance (West 1979 form)
            self.lat_var = (1.0 - a) * (self.lat_var + a * d * d)
        self.lat_samples += 1

    def lat_p95(self) -> float:
        """p95 estimate from the EWMA: mean + 1.645 sigma."""
        return self.lat_mean + 1.645 * math.sqrt(max(0.0, self.lat_var))

    def adopt_stats(self, stats: dict) -> bool:
        """Fold a fresh ``/v1/stats`` poll in; returns True when the
        replica RESTARTED since the last poll (new ``replica_id`` or
        ``uptime_seconds`` moved backwards) — its radix cache and any
        imported sessions are gone, so the router must drop affinity
        state pointing at it."""
        rid = str(stats.get("replica_id", ""))
        uptime = float(stats.get("uptime_seconds", 0.0))
        restarted = bool(
            self.replica_id and rid and (
                rid != self.replica_id or uptime < self.uptime
            )
        )
        self.replica_id = rid or self.replica_id
        self.uptime = uptime
        self.stats = stats
        self.last_poll = time.monotonic()
        digest = (stats.get("radix") or {}).get("digest") or {}
        self.granule = int(digest.get("granule", 0) or 0)
        self.chains = [list(c) for c in digest.get("paths", [])]
        return restarted

    def prefix_match(self, prompt: List[int],
                     want: Optional[List[str]] = None) -> int:
        """Longest advertised-prefix match in GRANULES (0 = none):
        hash the prompt's whole granules exactly like the replica does
        and walk each advertised chain. ``want`` takes the precomputed
        hash chain (:func:`want_hashes`) — the router computes it ONCE
        per request instead of per candidate replica (hashing a long
        prompt per replica per attempt is pure wasted proxy-path CPU)."""
        if not self.granule or not self.chains or not prompt:
            return 0
        if want is None:
            want = want_hashes(prompt, self.granule)
        if not want:
            return 0
        best = 0
        for chain in self.chains:
            k = 0
            while k < len(chain) and k < len(want) \
                    and chain[k] == want[k]:
                k += 1
            best = max(best, k)
        return best

    def load_score(self, tenant_class: str = "standard") -> float:
        """Least-loaded ordering key: waiting work + batch occupancy,
        weighted by KV pressure (a replica whose pool is nearly gone
        will shed or preempt next — route around it before it does).
        Latency-class requests penalize queue depth harder: their TTFT
        *is* the queue."""
        s = self.stats
        maxb = max(1, int(s.get("max_batch", 1)))
        queued = float(s.get("queued", 0))
        occupancy = (float(s.get("live_slots", 0))
                     + float(s.get("parked", 0))) / maxb
        kv = s.get("kv") or {}
        total = max(1, int(kv.get("total", 1)))
        kv_pressure = 1.0 - float(kv.get("free", 0)) / total
        queue_w = 2.0 if tenant_class == "latency" else 1.0
        return queue_w * queued / maxb + occupancy + kv_pressure

    def to_dict(self) -> dict:
        s = self.stats
        return {
            "url": self.url,
            "replica_id": self.replica_id,
            "uptime_seconds": self.uptime,
            "draining": self.draining,
            "ejected": self.ejected,
            "latency_p95_s": round(self.lat_p95(), 6),
            "latency_samples": self.lat_samples,
            "breaker_open": self.breaker.is_open(),
            "age_s": round(time.monotonic() - self.last_poll, 3)
            if self.last_poll else None,
            "queued": s.get("queued"),
            "live_slots": s.get("live_slots"),
            "parked": s.get("parked"),
            "kv_free": (s.get("kv") or {}).get("free"),
            "advertised_paths": len(self.chains),
        }


class _RouterHandler(BaseHTTPRequestHandler):
    router: "Router" = None  # type: ignore[assignment]

    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, payload: dict,
              retry_after: Optional[float] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(max(1, int(retry_after))))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length", "0") or 0)
        req = json.loads(self.rfile.read(n).decode() or "{}")
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        return req

    # ------------------------------------------------------------- GET

    def do_GET(self):
        r = type(self).router
        if self.path.startswith("/healthz"):
            self._send(200, {"status": "ok"})
        elif self.path.startswith("/readyz"):
            now = time.monotonic()
            n = sum(1 for rep in r.replicas()
                    if rep.alive(now, r.stale_after))
            if n:
                self._send(200, {"status": "ok", "replicas": n})
            else:
                self._send(503, {"status": "no routable replica"})
        elif self.path.startswith("/v1/stats"):
            self._send(200, r.stats())
        elif self.path.startswith("/metrics"):
            # the router's OWN registry in Prometheus exposition text —
            # the federation scrape target (obs/telemetry.py)
            from instaslice_tpu.metrics.metrics import render

            body = render(r.metrics).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/v1/debug/trace"):
            # debug parity with the replicas (serving/api_server.py):
            # router-side routing/migration spans, live
            qs = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query
            )
            try:
                self._send(200, debug_trace_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except LookupError as e:
                self._send(404, {"error": str(e)})
        elif self.path.startswith("/v1/debug/events"):
            qs = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query
            )
            try:
                self._send(200, debug_events_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
        elif self.path.startswith("/v1/debug/profile"):
            # router-side profiler ring: proxy/migration lane events
            # (no scheduler rounds — the replicas own those)
            qs = urllib.parse.parse_qs(
                urllib.parse.urlsplit(self.path).query
            )
            try:
                self._send(200, debug_profile_payload(qs))
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except LookupError as e:
                self._send(404, {"error": str(e)})
        elif self.path.startswith("/v1/debug/locks"):
            self._send(200, debug_locks_payload())
        elif self.path.rstrip("/").startswith("/v1/models"):
            # passthrough to any alive replica (they are identical)
            try:
                rep = r.pick_any()
                code, payload = r.http_json("GET", rep,
                                            self.path, None)
                self._send(code, payload)
            except NoReplica as e:
                self._send(503, {"error": str(e)})
        else:
            self._send(404, {"error": f"no route {self.path}"})

    # ------------------------------------------------------------ POST

    def do_POST(self):
        r = type(self).router
        if self.path.startswith("/v1/completions"):
            self._completions()
            return
        if self.path.startswith("/v1/replicas"):
            try:
                url = str(self._read_body().get("url", ""))
                if not url:
                    raise ValueError("body must carry {\"url\": ...}")
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            r.add_replica(url)
            self._send(200, {"added": url,
                             "replicas": len(r.replicas())})
            return
        if self.path.startswith("/v1/rebalance"):
            try:
                body = self._read_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            out = r.rebalance(n=int(body.get("n", 1)))
            self._send(200, out)
            return
        self._send(404, {"error": f"no route {self.path}"})

    def do_DELETE(self):
        r = type(self).router
        if self.path.startswith("/v1/replicas"):
            try:
                body = self._read_body()
                url = str(body.get("url", ""))
                if not url:
                    raise ValueError("body must carry {\"url\": ...}")
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            out = r.remove_replica(
                url, migrate=bool(body.get("migrate", True)),
                budget=body.get("budget"),
            )
            self._send(200, out)
            return
        self._send(404, {"error": f"no route {self.path}"})

    # ----------------------------------------------------- completions

    def _completions(self) -> None:
        r = type(self).router
        try:
            body = self._read_body()
        except (ValueError, json.JSONDecodeError) as e:
            self._send(400, {"error": str(e)})
            return
        header = self.headers.get("X-Trace-Id")
        tid = (header if header and TRACE_ID_SAFE.match(header)
               else new_trace_id())
        tenant = self.headers.get("X-Tenant") or body.get("tenant") \
            or ""
        session_id = self.headers.get("X-Session-Id") \
            or body.get("session") or ""
        # protocol fields are the ROUTER'S to mint, never a client's:
        # a forwarded {"resume": rid} would claim whatever imported
        # session happens to be awaiting resume on a replica — another
        # user's in-flight conversation
        body.pop("resume", None)
        prompt = body.get("prompt")
        prompt = prompt if isinstance(prompt, list) else []
        stream = bool(body.get("stream", False))
        ctx = _ProxyContext(r, self, body, tid, str(tenant),
                            str(session_id), stream)
        try:
            ctx.run(prompt)
        except NoReplica as e:
            r.count_request("no-replica")
            self._send(503, {"error": str(e)}, retry_after=1.0)
        except (BrokenPipeError, ConnectionError, OSError):
            # the CLIENT went away mid-proxy: nothing to send
            r.count_request("client-gone")
            self.close_connection = True


class _ProxyContext:
    """One proxied completion: routing, forwarding, retry-before-
    first-token, and mid-stream migration stitching. Lives on the
    handler thread that owns the client connection — the thread that
    sees a migration terminal is exactly the thread that imports the
    session into the destination and splices the streams."""

    def __init__(self, router: "Router", handler: _RouterHandler,
                 body: dict, trace_id: str, tenant: str,
                 session_id: str, stream: bool) -> None:
        self.r = router
        self.h = handler
        self.body = body
        self.trace_id = trace_id
        self.tenant = tenant
        self.session_id = session_id
        self.stream = stream
        self.session_key = f"sk-{uuid.uuid4().hex[:16]}"
        self.tokens_forwarded = 0
        self.headers_sent = False
        self.errored = False        # a terminal error already counted
        self.tried: List[str] = []
        self.hops = 0               # migrations this request survived
        #: tokens recovered from a migration blob when the import path
        #: fell back to re-prefill (sync mode accumulates, stream emits)
        self._prefix_tokens: List[int] = []

    # ------------------------------------------------------- plumbing

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json",
             "X-Session-Key": self.session_key,
             "X-Trace-Id": self.trace_id}
        if self.tenant:
            h["X-Tenant"] = self.tenant
        return h

    def _open(self, rep: Replica, payload: dict,
              timeout: Optional[float] = None):
        """POST a completion to ``rep``; returns the live response
        object (streaming reads follow). Breaker-audited. ``timeout``
        overrides the default socket deadline (migration hops use the
        shorter ``migrate_timeout`` so a wedged destination falls back
        to a survivor instead of holding the client)."""
        rep.breaker.check()
        req = urllib.request.Request(
            rep.url + "/v1/completions",
            data=json.dumps(payload).encode(),
            headers=self._headers(), method="POST",
        )
        try:
            self.r.maybe_nemesis(rep)
            resp = urllib.request.urlopen(
                req, timeout=timeout or self.r.request_timeout
            )
        except urllib.error.HTTPError:
            raise                       # terminal HTTP status: not a
        except _TRANSPORT_EXC:          # breaker event
            self.r.breaker_fail(rep)
            raise
        rep.breaker.ok()
        return resp

    # ------------------------------------------------------ main flow

    def run(self, prompt: List[int]) -> None:
        payload = dict(self.body)
        t0 = time.perf_counter()
        for attempt in range(self.r.max_retries + 1):
            try:
                rep, policy = self.r.route(
                    prompt, self.tenant, self.session_id,
                    exclude=self.tried,
                )
            except NoReplica:
                if not self.headers_sent:
                    raise       # handler sends the clean 503
                break           # mid-stream: terminal error below
            get_tracer().record(
                "router.route", (time.perf_counter() - t0) * 1e3,
                trace_id=self.trace_id, replica=rep.url,
                policy=policy, attempt=attempt,
            )
            get_profiler().event(
                "proxy", "route",
                dur_ms=(time.perf_counter() - t0) * 1e3,
                replica=rep.url, policy=policy, attempt=attempt,
                trace_id=self.trace_id,
            )
            self.r.count_routed(policy)
            self.tried.append(rep.url)
            try:
                resp = self._open(rep, payload)
            except urllib.error.HTTPError as e:
                if e.code in (429, 503) and not self.tokens_forwarded \
                        and attempt < self.r.max_retries:
                    e.read()
                    continue        # shed/draining: try a peer
                self._relay_http_error(e)
                return
            except _TRANSPORT_EXC as e:
                if not self.tokens_forwarded \
                        and attempt < self.r.max_retries:
                    continue
                self.r.count_request("transport-error")
                self._client_error(502, f"replica {rep.url}: {e}")
                return
            except CircuitOpen:
                continue
            with resp:
                if self.stream:
                    done = self._relay_stream(rep, resp)
                else:
                    done = self._relay_sync(rep, resp)
            if done:
                if not self.errored:
                    if self.session_id:
                        self.r.pin_session(self.session_id,
                                           self.tried[-1])
                    self.r.count_request("ok" if self.hops == 0
                                         else "ok-migrated")
                return
        self.r.count_request("no-replica")
        if not self.headers_sent:
            self.h._send(503, {"error": "no replica accepted the "
                                        "request"}, retry_after=1.0)
        else:
            self._write_event({"error": "no replica accepted the "
                                        "request"})
            self._write_event("[DONE]")

    # ------------------------------------------------------ sync path

    def _relay_sync(self, rep: Replica, resp) -> bool:
        payload = json.loads(resp.read())
        if payload.get("object") == "text_completion.migration":
            return self._continue_session(rep, payload["session"])
        # merge tokens a migration FALLBACK already accumulated
        if self._prefix_tokens:
            for c in payload.get("choices", []):
                c["token_ids"] = self._prefix_tokens + c["token_ids"]
            usage = payload.get("usage")
            if usage:
                usage["completion_tokens"] = (
                    usage.get("completion_tokens", 0)
                    + len(self._prefix_tokens)
                )
        self.h._send(resp.status, payload)
        self.headers_sent = True
        return True

    # ---------------------------------------------------- stream path

    def _begin_stream(self) -> None:
        if self.headers_sent:
            return
        self.h.send_response(200)
        self.h.send_header("Content-Type", "text/event-stream")
        self.h.send_header("Cache-Control", "no-cache")
        self.h.send_header("X-Trace-Id", self.trace_id)
        self.h.end_headers()
        self.headers_sent = True

    def _write_event(self, payload) -> None:
        data = payload if isinstance(payload, str) else json.dumps(
            payload
        )
        self.h.wfile.write(f"data: {data}\n\n".encode())
        self.h.wfile.flush()

    def _relay_stream(self, rep: Replica, resp) -> bool:
        """Forward SSE events verbatim; a migration terminal hands off
        to :meth:`_continue_session` (the [DONE] after it is consumed,
        not forwarded — the CLIENT's stream continues on the
        destination's events). Returns False to ask :meth:`run` for a
        re-route: a streaming request sheds IN-BAND (the replica sent
        its SSE headers before admission, so a drain/shed arrives as
        an error event, not a 503) — with zero tokens forwarded a peer
        can still serve the whole request."""
        self._begin_stream()
        buf = b""
        plan = get_nemesis()
        while True:
            try:
                chunk = resp.read1(65536)
            except _TRANSPORT_EXC as e:
                self.r.breaker_fail(rep)
                self._client_error(502, f"replica stream died: {e}")
                return True         # client already has a terminal
            if plan is not None and chunk:
                # nemesis slow-transfer throttling on the stream edge
                plan.throttle_sleep("router", f"replica:{rep.url}",
                                    len(chunk))
            if not chunk:
                # upstream ended without [DONE]: surface, don't hang
                self._write_event({"error": "replica stream ended "
                                            "early"})
                self._write_event("[DONE]")
                return True
            buf += chunk
            while b"\n\n" in buf:
                event, buf = buf.split(b"\n\n", 1)
                line = event.decode().strip()
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    self._write_event("[DONE]")
                    return True
                payload = json.loads(data)
                if payload.get("object") == \
                        "text_completion.migration":
                    return self._continue_session(
                        rep, payload["session"]
                    )
                if "error" in payload and "choices" not in payload:
                    if not self.tokens_forwarded:
                        # in-band shed (drain/queue-full on a stream
                        # that was never admitted): retry on a peer
                        # instead of relaying a failure the fleet can
                        # absorb
                        log.info("re-routing in-band stream error "
                                 "from %s: %s", rep.url,
                                 payload["error"])
                        return False
                    self._write_event(payload)
                    self._write_event("[DONE]")
                    return True
                for c in payload.get("choices", []):
                    self.tokens_forwarded += len(
                        c.get("token_ids") or []
                    )
                self._write_event(payload)

    # ------------------------------------------------------ migration

    def _continue_session(self, source: Replica, blob: dict) -> bool:
        """The session left ``source`` mid-decode — import it into a
        peer and splice the resumed response into the client's, so the
        client sees ONE continuous completion. Falls back to
        re-prefill (prompt + generated tokens as a fresh prompt) when
        no peer accepts the import; the radix cache usually makes even
        that cheap."""
        self.hops += 1
        t0 = time.perf_counter()
        dests = self.r.migration_destinations(
            exclude=[source.url], prompt=blob.get("prompt") or []
        )
        for dest in dests:
            try:
                code, imp = self.r.http_json(
                    "POST", dest, "/v1/sessions/import",
                    {"session": blob},
                    timeout=self.r.migrate_timeout,
                )
                if code != 200:
                    continue
                payload = {"resume": imp["rid"], "stream": self.stream}
                resp = self._open(dest, payload,
                                  timeout=self.r.migrate_timeout)
                # the hop timeout bounded the handshake; the RESUMED
                # stream gets the normal request deadline back — a
                # migrated session legitimately parked/queued on its
                # destination between tokens is not a wedged hop
                try:
                    resp.fp.raw._sock.settimeout(
                        self.r.request_timeout
                    )
                except AttributeError:
                    log.debug("could not widen resumed-stream timeout")
            except (urllib.error.HTTPError, *_TRANSPORT_EXC,
                    CircuitOpen) as e:
                log.warning("migration to %s failed: %s", dest.url, e)
                continue
            get_tracer().record(
                "router.migrate",
                (time.perf_counter() - t0) * 1e3,
                trace_id=self.trace_id, source=source.url,
                dest=dest.url, mode="resume",
                tokens_in=len(blob.get("generated", [])),
            )
            get_profiler().event(
                "migrate", "resume",
                dur_ms=(time.perf_counter() - t0) * 1e3,
                source=source.url, dest=dest.url,
                trace_id=self.trace_id,
            )
            self.r.count_migration("resumed")
            self.r.note_migrated_trace(self.trace_id)
            self.tried.append(dest.url)
            if self.session_id:
                self.r.pin_session(self.session_id, dest.url)
            with resp:
                if self.stream:
                    return self._relay_stream(dest, resp)
                return self._relay_sync(dest, resp)
        # ---- fallback: re-prefill the full history on any replica —
        # slower (a prefill the migration existed to skip) but the
        # request still terminates cleanly with the right tokens
        return self._fallback_reprefill(source, blob, t0)

    def _fallback_reprefill(self, source: Replica, blob: dict,
                            t0: float) -> bool:
        generated = [int(t) for t in blob.get("generated", [])]
        sent = int(blob.get("sent", 0))
        remaining = int(blob.get("remaining_budget", 0))
        if self.stream:
            self._begin_stream()
            held = generated[sent:]
            if held:
                # tokens the source decoded but never streamed ride a
                # synthetic delta — the client must not lose them
                self._write_event({
                    "object": "text_completion",
                    "choices": [{"index": 0, "token_ids": held,
                                 "finish_reason": None}],
                })
                self.tokens_forwarded += len(held)
        else:
            self._prefix_tokens = generated
        if remaining < 1:
            if self.stream:
                self._write_event({
                    "object": "text_completion",
                    "choices": [{"index": 0, "token_ids": [],
                                 "finish_reason": "max_new_tokens"}],
                })
                self._write_event("[DONE]")
            else:
                self.h._send(200, {
                    "object": "text_completion",
                    "choices": [{"index": 0,
                                 "token_ids": generated,
                                 "finish_reason": "max_new_tokens"}],
                    "usage": {"prompt_tokens":
                              len(blob.get("prompt", [])),
                              "completion_tokens": len(generated)},
                })
                self.headers_sent = True
            self.r.count_migration("fallback")
            return True
        payload = {
            "prompt": [int(t) for t in blob.get("prompt", [])]
            + generated,
            "max_tokens": remaining,
            "stream": self.stream,
        }
        # the continuation must keep the ORIGINAL request's semantics:
        # stop sequences, adapter, and logprobs ride the client body
        # (a re-prefill that silently switched to the base model or
        # decoded past a stop would return wrong tokens with a 200)
        for key in ("stop", "adapter", "logprobs"):
            if key in self.body:
                payload[key] = self.body[key]
        for attempt in range(self.r.max_retries + 1):
            try:
                dest, _policy = self.r.route(
                    payload["prompt"], self.tenant, "",
                    exclude=[source.url] if attempt == 0 else [],
                )
                resp = self._open(dest, payload)
            except (NoReplica, urllib.error.HTTPError,
                    *_TRANSPORT_EXC, CircuitOpen) as e:
                log.warning("re-prefill fallback attempt failed: %s",
                            e)
                continue
            get_tracer().record(
                "router.migrate",
                (time.perf_counter() - t0) * 1e3,
                trace_id=self.trace_id, source=source.url,
                dest=dest.url, mode="reprefill",
                tokens_in=len(generated),
            )
            get_profiler().event(
                "migrate", "reprefill",
                dur_ms=(time.perf_counter() - t0) * 1e3,
                source=source.url, dest=dest.url,
                trace_id=self.trace_id,
            )
            self.r.count_migration("fallback")
            with resp:
                if self.stream:
                    return self._relay_stream(dest, resp)
                return self._relay_sync(dest, resp)
        self.r.count_migration("lost")
        self._client_error(502, "session migration failed and no "
                                "replica accepted the re-prefill")
        return True

    # --------------------------------------------------------- errors

    def _relay_http_error(self, e) -> None:
        try:
            payload = json.loads(e.read().decode())
        except (ValueError, OSError):
            payload = {"error": str(e.reason)}
        outcome = {429: "shed", 503: "unavailable"}.get(
            e.code, "upstream-error"
        )
        self.r.count_request(outcome)
        if self.headers_sent:
            self._write_event({"error": payload.get("error",
                                                    str(e.reason))})
            self._write_event("[DONE]")
            return
        self.h._send(e.code, payload,
                     retry_after=1.0 if e.code in (429, 503) else None)
        self.headers_sent = True

    def _client_error(self, code: int, msg: str) -> None:
        self.errored = True
        if self.headers_sent:
            self._write_event({"error": msg})
            self._write_event("[DONE]")
            return
        self.h._send(code, {"error": msg})
        self.headers_sent = True


class Router:
    """The fleet front-end (module docstring has the full story).

    ``replicas``: initial replica base URLs. ``poll_interval`` paces
    the stats poll loop; ``stale_after`` is how long a replica may go
    unpolled before it stops being routable; ``kv_weight`` scales KV
    pressure in the load score (via :meth:`Replica.load_score`).
    ``metrics``: a :class:`~instaslice_tpu.metrics.metrics.
    RouterMetrics` (defaulted)."""

    #: stats-poll failure backoff (capped decorrelated jitter; the
    #: kube/real.py policy at router scale)
    poll_backoff_base = 0.05
    poll_backoff_cap = 2.0
    retry_after_cap = 30.0

    # ---- thread model (slicecheck-verified): replica table, session
    # affinity, and the counters are shared between the poll loop, the
    # HTTP handler threads, and admin calls — all under router.state
    _replicas: guarded_by("router.state")
    _sessions: guarded_by("router.state")
    requests: guarded_by("router.state")
    routed: guarded_by("router.state")
    migrations: guarded_by("router.state")
    ejections: guarded_by("router.state")
    hedges: guarded_by("router.state")

    def __init__(self, replicas=(), host: str = "127.0.0.1",
                 port: int = 0, poll_interval: float = 0.25,
                 stale_after: float = 3.0, request_timeout: float = 300.0,
                 max_retries: int = 2, session_ttl: float = 600.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown: float = 2.0, metrics=None,
                 migrate_timeout: Optional[float] = None,
                 eject_factor: float = 3.0,
                 readmit_factor: float = 1.5,
                 eject_min_samples: int = 8,
                 eject_floor_s: float = 0.02,
                 hedge_after: float = 0.5) -> None:
        self.poll_interval = poll_interval
        self.stale_after = stale_after
        self.request_timeout = request_timeout
        self.max_retries = max_retries
        self.session_ttl = session_ttl
        # gray-failure ejection knobs (docs/RECOVERY.md "Partitions &
        # gray failures"): a replica whose poll-latency EWMA p95 exceeds
        # eject_factor × the fleet median (of the OTHER routable
        # replicas) is ejected even at 100% success; re-admitted at
        # readmit_factor × median (hysteresis). eject_floor_s guards
        # microsecond-scale fleets from noise ejections;
        # eject_factor <= 0 disables the sweep. hedge_after is the
        # hedged-retry delay for idempotent stats polls (second request
        # fired if the first hasn't answered; first result wins);
        # <= 0 disables hedging.
        self.eject_factor = eject_factor
        self.readmit_factor = readmit_factor
        self.eject_min_samples = eject_min_samples
        self.eject_floor_s = eject_floor_s
        self.hedge_after = hedge_after
        # self-healing watchdog (docs/RECOVERY.md): bound on EACH
        # migration hop (import POST + resume handshake). Without it a
        # destination that accepted the import and then wedged (crashed
        # scheduler thread) would hold the client the full
        # request_timeout; with it the hop times out, the next survivor
        # is tried, and the re-prefill fallback terminates the request
        # with the right tokens. The orphaned import on the wedged
        # replica is swept engine-side after its import TTL.
        # 0 (or negative) = disabled: hops get the full
        # request_timeout — normalized HERE so every consumer sees one
        # semantic (a raw 0 reaching urlopen would mean non-blocking
        # sockets and instantly failing imports).
        from instaslice_tpu.utils.envutil import env_float

        if migrate_timeout is None:
            migrate_timeout = env_float(
                "TPUSLICE_ROUTER_MIGRATE_TIMEOUT", 15.0)
        if migrate_timeout <= 0:
            migrate_timeout = request_timeout
        self.migrate_timeout = migrate_timeout
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._lock = named_lock("router.state")
        self._replicas: Dict[str, Replica] = {}
        #: session affinity: session id → (replica url, last-used ts)
        self._sessions: Dict[str, Tuple[str, float]] = {}
        # counters (also exported via RouterMetrics)
        self.requests: Dict[str, int] = {}
        self.routed: Dict[str, int] = {}
        self.migrations: Dict[str, int] = {}
        #: gray-failure accounting: replica url → ejection count, and
        #: hedged stats polls fired / won (won = the hedge answered
        #: while the primary was still in flight)
        self.ejections: Dict[str, int] = {}
        self.hedges: Dict[str, int] = {"fired": 0, "won": 0}
        #: trace ids of requests that survived ≥1 migration — the
        #: bench's oracle-comparison hook (bounded ring)
        self.migrated_traces: List[str] = []
        if metrics is None:
            from instaslice_tpu.metrics.metrics import RouterMetrics

            metrics = RouterMetrics()
        self.metrics = metrics
        for url in replicas:
            self.add_replica(url)
        handler = type("BoundRouterHandler", (_RouterHandler,),
                       {"router": self})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="router-http",
            daemon=True,
        )
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, name="router-poll", daemon=True
        )

    # ---------------------------------------------------------- lifecycle

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "Router":
        self.poll_now()
        self._poller.start()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._srv.shutdown()
        self._srv.server_close()
        self._poller.join(timeout=5)
        self._thread.join(timeout=5)

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ replicas

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def add_replica(self, url: str) -> Replica:
        rep = Replica(url, self.breaker_threshold,
                      self.breaker_cooldown)
        with self._lock:
            existing = self._replicas.get(rep.url)
            if existing is not None:
                existing.draining = False
                return existing
            self._replicas[rep.url] = rep
            n = len(self._replicas)
        self.metrics.replicas.set(n)
        self._poll_one(rep)
        return rep

    def remove_replica(self, url: str, migrate: bool = True,
                       budget: Optional[float] = None,
                       deadline_s: float = 30.0) -> dict:
        """Drain-without-503 replica removal: mark the replica
        undroutable, drain it with ``migrate`` so every in-flight
        session leaves through its own response (the proxy threads
        import them into peers), wait for quiesce, drop it from the
        pool. The replica process itself is the operator's to stop."""
        url = url.rstrip("/")
        with self._lock:
            rep = self._replicas.get(url)
        if rep is None:
            return {"removed": False, "error": f"unknown replica {url}"}
        rep.draining = True
        body = {"migrate": migrate}
        if budget is not None:
            body["budget"] = budget
        migrated = 0
        pause = 0.0
        for _attempt in range(3):
            try:
                code, out = self.http_json("POST", rep, "/v1/drain",
                                           body)
            except _TRANSPORT_EXC as e:
                log.warning("drain of %s failed (%s): removing anyway",
                            url, e)
                break
            if code == 200:
                migrated = int(out.get("migrated", 0))
                break
            if code not in (429, 503):
                break
            # pushed back: honor Retry-After with jittered backoff
            pause = self._next_backoff(pause, rep.retry_after_hint)
            if self._stop.wait(pause):
                break
        # wait for the replica to go idle (its exported sessions are
        # resumed elsewhere by the proxy threads; queued requests shed
        # and retried by their own handlers). Jittered pacing, not a
        # fixed tick: N concurrent removals re-polling in lockstep is
        # exactly the stampede the backoff policy exists to break.
        deadline = time.monotonic() + deadline_s
        idle = False
        pause = 0.0
        while time.monotonic() < deadline:
            try:
                _code, s = self.http_json("GET", rep, "/v1/stats",
                                          None)
                if not (s.get("live_slots") or s.get("queued")
                        or s.get("parked")):
                    idle = True
                    break
            except _TRANSPORT_EXC:
                idle = True            # it already went away
                break
            pause = self._next_backoff(
                min(pause, 0.2), rep.retry_after_hint
            )
            if self._stop.wait(pause):
                break
        with self._lock:
            self._replicas.pop(url, None)
            self._sessions = {
                sid: (u, ts) for sid, (u, ts) in self._sessions.items()
                if u != url
            }
            n = len(self._replicas)
        self.metrics.replicas.set(n)
        return {"removed": True, "migrated": migrated, "idle": idle,
                "replicas": n}

    # ------------------------------------------------------------- polling

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self.poll_now()
            self._gray_sweep()
            self._sweep_sessions()

    def poll_now(self) -> None:
        for rep in self.replicas():
            self._poll_one(rep)

    def _next_backoff(self, prev: float,
                      retry_after: Optional[float] = None) -> float:
        """Capped decorrelated-jitter backoff, stretched to honor a
        server Retry-After — the kube/real.py ``_backoff_sleep`` policy
        without the sleep (poll pacing owns the wait)."""
        delay = min(
            self.poll_backoff_cap,
            random.uniform(self.poll_backoff_base,
                           max(prev, self.poll_backoff_base) * 3),
        )
        if retry_after is not None:
            delay = max(delay, min(retry_after, self.retry_after_cap))
        return delay

    def _note_poll_failure(self, rep: Replica,
                           retry_after: Optional[float]) -> None:
        rep.poll_backoff = self._next_backoff(rep.poll_backoff,
                                              retry_after)
        rep.poll_next = time.monotonic() + rep.poll_backoff

    def _poll_one(self, rep: Replica) -> None:
        if rep.breaker.is_open():
            return
        if time.monotonic() < rep.poll_next:
            return  # backing off a recent failure (jittered, not fixed)
        try:
            code, stats, lat = self._hedged_stats(rep)
        except _TRANSPORT_EXC as e:
            log.debug("poll of %s failed: %s", rep.url, e)
            self.breaker_fail(rep)
            self._note_poll_failure(rep, None)
            return
        if code != 200:
            # 429/503 push back: honor Retry-After before re-polling
            self._note_poll_failure(
                rep,
                rep.retry_after_hint if code in (429, 503) else None,
            )
            return
        rep.poll_backoff = 0.0
        rep.poll_next = 0.0
        rep.breaker.ok()
        rep.observe_latency(lat)
        self.metrics.replica_latency.labels(replica=rep.url).set(
            rep.lat_p95()
        )
        if rep.adopt_stats(stats):
            log.warning("replica %s RESTARTED: dropping its session "
                        "affinities", rep.url)
            with self._lock:
                self._sessions = {
                    sid: (u, ts)
                    for sid, (u, ts) in self._sessions.items()
                    if u != rep.url
                }

    def _hedged_stats(self, rep: Replica):
        """GET /v1/stats with one hedged retry: if the primary hasn't
        answered within ``hedge_after`` seconds a second (idempotent)
        request races it and the first result wins — a gray replica's
        slow answer can't stall the poll loop's view of it. Returns
        (code, payload, winner_latency_s)."""
        if self.hedge_after <= 0:
            t0 = time.perf_counter()
            code, payload = self.http_json("GET", rep, "/v1/stats",
                                           None)
            return code, payload, time.perf_counter() - t0
        box: dict = {}
        done = threading.Event()

        def primary():
            t0 = time.perf_counter()
            try:
                code, payload = self.http_json("GET", rep, "/v1/stats",
                                               None)
                box["first"] = (code, payload,
                                time.perf_counter() - t0)
            except _TRANSPORT_EXC as e:
                box["exc"] = e
            done.set()

        th = threading.Thread(target=primary, name="router-poll-first",
                              daemon=True)
        th.start()
        if done.wait(self.hedge_after):
            if "exc" in box:
                raise box["exc"]
            return box["first"]
        with self._lock:
            self.hedges["fired"] += 1
        t0 = time.perf_counter()
        try:
            code, payload = self.http_json("GET", rep, "/v1/stats",
                                           None)
            hedge = (code, payload, time.perf_counter() - t0)
        except _TRANSPORT_EXC:
            # hedge died too: fall back to whatever the primary does
            done.wait(self.request_timeout)
            if "first" in box:
                return box["first"]
            raise
        if done.is_set() and "first" in box:
            return box["first"]  # primary got there first after all
        with self._lock:
            self.hedges["won"] += 1
        self.count_request("hedged-ok")
        return hedge

    # ------------------------------------------------- gray-failure eject

    def _gray_sweep(self) -> None:
        """Eject replicas whose latency EWMA p95 degrades past
        ``eject_factor`` × the fleet median even at 100% success (the
        gray failure a circuit breaker never sees), drain their
        sessions through the live-migration path, and re-admit at
        ``readmit_factor`` × median once the EWMA recovers
        (hysteresis). Never ejects below 2 routable peers."""
        if self.eject_factor <= 0:
            return
        seasoned = [r for r in self.replicas()
                    if r.lat_samples >= self.eject_min_samples
                    and not r.breaker.is_open() and not r.draining]
        healthy = [r for r in seasoned if not r.ejected]
        for rep in seasoned:
            others = [h.lat_p95() for h in healthy if h is not rep]
            if not others:
                continue
            med = _median(others)
            p95 = rep.lat_p95()
            if not rep.ejected:
                if (len(healthy) >= 2
                        and p95 > max(self.eject_floor_s,
                                      self.eject_factor * med)):
                    self._eject(rep, p95, med)
                    healthy.remove(rep)
            elif p95 <= max(self.eject_floor_s,
                            self.readmit_factor * med):
                self._readmit(rep, p95, med)
                healthy.append(rep)

    def _eject(self, rep: Replica, p95: float, med: float) -> None:
        rep.ejected = True
        with self._lock:
            self.ejections[rep.url] = self.ejections.get(rep.url, 0) + 1
            # its radix cache will be cold-ish on return and its
            # sessions are about to migrate out: drop the affinities now
            self._sessions = {
                sid: (u, ts) for sid, (u, ts) in self._sessions.items()
                if u != rep.url
            }
        self.metrics.replica_ejections.inc()
        log.warning(
            "replica %s gray-EJECTED: latency p95 %.4fs > %.1fx fleet "
            "median %.4fs (success rate untouched); draining sessions",
            rep.url, p95, self.eject_factor, med,
        )
        get_journal().emit(
            "router",
            reason=REASON_REPLICA_EJECTED,
            object_ref=f"replica/{rep.url}",
            message=(f"latency p95 {p95:.4f}s vs fleet median "
                     f"{med:.4f}s; sessions draining via migration"),
        )
        # drain (migrate) off the poll thread: a gray replica answers
        # SLOWLY, and the sweep must not stall behind it
        threading.Thread(
            target=self._drain_ejected, args=(rep,),
            name="router-eject-drain", daemon=True,
        ).start()

    def _drain_ejected(self, rep: Replica) -> None:
        pause = 0.0
        for _ in range(3):
            try:
                code, _out = self.http_json(
                    "POST", rep, "/v1/drain", {"migrate": True},
                    timeout=self.migrate_timeout,
                )
            except _TRANSPORT_EXC as e:
                log.warning("drain of ejected %s failed: %s",
                            rep.url, e)
                return
            if code not in (429, 503):
                return
            pause = self._next_backoff(pause, rep.retry_after_hint)
            if self._stop.wait(pause):
                return

    def _readmit(self, rep: Replica, p95: float, med: float) -> None:
        try:
            # lift the replica-side drain so it admits again
            self.http_json("DELETE", rep, "/v1/drain", {})
        except _TRANSPORT_EXC as e:
            log.warning("undrain of %s failed (%s); retrying next "
                        "sweep", rep.url, e)
            return
        rep.ejected = False
        log.info("replica %s re-admitted: latency p95 %.4fs back "
                 "within %.1fx fleet median %.4fs", rep.url, p95,
                 self.readmit_factor, med)
        get_journal().emit(
            "router",
            reason=REASON_REPLICA_READMITTED,
            object_ref=f"replica/{rep.url}",
            message=(f"latency p95 {p95:.4f}s recovered vs fleet "
                     f"median {med:.4f}s"),
        )

    def _sweep_sessions(self) -> None:
        now = time.monotonic()
        with self._lock:
            self._sessions = {
                sid: (u, ts) for sid, (u, ts) in self._sessions.items()
                if now - ts <= self.session_ttl
            }

    # ------------------------------------------------------------- routing

    def route(self, prompt: List[int], tenant: str = "",
              session_id: str = "",
              exclude=()) -> Tuple[Replica, str]:
        """Pick a replica for a fresh completion; returns (replica,
        policy) where policy names which rule fired: ``session`` /
        ``prefix`` / ``least-loaded``. Raises :class:`NoReplica`."""
        now = time.monotonic()
        tenant_class = self._tenant_class(tenant)
        cands = [rep for rep in self.replicas()
                 if rep.alive(now, self.stale_after)
                 and rep.url not in exclude]
        if not cands:
            ejected = sum(1 for rep in self.replicas() if rep.ejected)
            raise NoReplica(
                "no routable replica (all dead, draining, "
                "circuit-broken, or already tried"
                + (f"; {ejected} gray-ejected" if ejected else "")
                + ")"
            )
        # 1. session affinity: a multi-turn follow-up goes back to the
        # replica whose radix cache holds its history
        if session_id:
            with self._lock:
                hit = self._sessions.get(session_id)
            if hit is not None:
                for rep in cands:
                    if rep.url == hit[0]:
                        self.pin_session(session_id, rep.url)
                        return rep, "session"
        # 2. prefix-cache affinity via the shadow index (the prompt's
        # granule hashes computed once per distinct granule size, not
        # once per replica)
        want_by_g: Dict[int, List[str]] = {}
        best, best_match = None, 0
        for rep in cands:
            if rep.granule not in want_by_g:
                want_by_g[rep.granule] = want_hashes(prompt,
                                                    rep.granule)
            m = rep.prefix_match(prompt, want_by_g[rep.granule])
            if m > best_match or (
                m == best_match and m > 0 and best is not None
                and rep.load_score(tenant_class)
                < best.load_score(tenant_class)
            ):
                best, best_match = rep, m
        if best is not None and best_match > 0:
            return best, "prefix"
        # 3. least-loaded weighted by KV pressure + tenant class
        rep = min(cands, key=lambda c: c.load_score(tenant_class))
        return rep, "least-loaded"

    def _tenant_class(self, tenant: str) -> str:
        if not tenant:
            return "standard"
        for rep in self.replicas():
            cls = (rep.stats.get("tenant_classes") or {}).get(tenant)
            if cls:
                return cls
        return "standard"

    def pick_any(self) -> Replica:
        now = time.monotonic()
        for rep in self.replicas():
            if rep.alive(now, self.stale_after):
                return rep
        raise NoReplica("no routable replica")

    def migration_destinations(self, exclude=(),
                               prompt=None) -> List[Replica]:
        """Import destinations for a migrating session, best first:
        prefix affinity over the session's prompt, then least load."""
        now = time.monotonic()
        cands = [rep for rep in self.replicas()
                 if rep.alive(now, self.stale_after)
                 and rep.url not in exclude]
        want_by_g: Dict[int, List[str]] = {}
        for rep in cands:
            if rep.granule not in want_by_g:
                want_by_g[rep.granule] = want_hashes(prompt or [],
                                                     rep.granule)
        cands.sort(key=lambda c: (
            -c.prefix_match(prompt or [], want_by_g[c.granule]),
            c.load_score(),
        ))
        return cands

    def pin_session(self, session_id: str, url: str) -> None:
        with self._lock:
            self._sessions[session_id] = (url, time.monotonic())

    # ----------------------------------------------------------- rebalance

    def rebalance(self, n: int = 1) -> dict:
        """Move up to ``n`` sessions off the most loaded replica: its
        scheduler exports them through their in-flight responses, and
        the proxy threads import each into the least-loaded peer —
        live, mid-stream, no client-visible interruption."""
        now = time.monotonic()
        cands = [rep for rep in self.replicas()
                 if rep.alive(now, self.stale_after)]
        if len(cands) < 2:
            return {"requested": 0, "error": "need >= 2 replicas"}
        hot = max(cands, key=lambda c: c.load_score())
        try:
            _code, out = self.http_json(
                "POST", hot, "/v1/sessions/export", {"limit": n}
            )
        except _TRANSPORT_EXC as e:
            return {"requested": 0, "error": str(e)}
        return {"requested": int(out.get("migrated", 0)),
                "replica": hot.url}

    # ---------------------------------------------------------- accounting

    def maybe_nemesis(self, rep: Replica) -> None:
        """Consult the global nemesis plan on the router→replica edge
        (``router>replica:<url>`` — partitions raise a connection
        error the breaker/retry machinery already handles; latency
        rules sleep, which is exactly how a gray replica is
        injected)."""
        plan = get_nemesis()
        if plan is not None:
            plan.before_request("router", f"replica:{rep.url}")

    def breaker_fail(self, rep: Replica) -> None:
        """Record a transport failure against ``rep``'s breaker and —
        when THIS failure opened the circuit — log and count it. Every
        failure site goes through here (poll loop and request path
        alike), or opens caused by live traffic would be invisible to
        ``tpuslice_router_breaker_open_total``."""
        if rep.breaker.fail():
            log.warning("replica %s circuit OPEN", rep.url)
            self.metrics.breaker_opens.inc()

    def count_request(self, outcome: str) -> None:
        with self._lock:
            self.requests[outcome] = self.requests.get(outcome, 0) + 1
        self.metrics.requests.labels(outcome=outcome).inc()

    def count_routed(self, policy: str) -> None:
        with self._lock:
            self.routed[policy] = self.routed.get(policy, 0) + 1
        self.metrics.routed.labels(policy=policy).inc()

    def count_migration(self, outcome: str) -> None:
        with self._lock:
            self.migrations[outcome] = (
                self.migrations.get(outcome, 0) + 1
            )
        self.metrics.migrations.labels(outcome=outcome).inc()

    def note_migrated_trace(self, trace_id: str) -> None:
        with self._lock:
            self.migrated_traces.append(trace_id)
            del self.migrated_traces[:-256]

    def stats(self) -> dict:
        now = time.monotonic()
        reps = self.replicas()
        with self._lock:
            out = {
                "replicas": {rep.url: rep.to_dict() for rep in reps},
                "routable": sum(
                    1 for rep in reps
                    if rep.alive(now, self.stale_after)
                ),
                "sessions": len(self._sessions),
                "requests": dict(self.requests),
                "routed": dict(self.routed),
                "migrations": dict(self.migrations),
                "ejections": dict(self.ejections),
                "hedges": dict(self.hedges),
                "migrated_traces": list(self.migrated_traces),
            }
        return out

    # ------------------------------------------------------------ plumbing

    def http_json(self, method: str, rep: Replica, path: str,
                  body: Optional[dict], timeout: float = 10.0):
        """One JSON round-trip to a replica (control-plane calls:
        stats polls, drains, imports). Breaker-audited; HTTP error
        statuses return (code, payload) rather than raising — a 400
        from an import is an ANSWER (version mismatch), not a
        transport failure."""
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            rep.url + path, data=data,
            headers={"Content-Type": "application/json"},
            method=method,
        )
        try:
            self.maybe_nemesis(rep)
            with urllib.request.urlopen(req, timeout=timeout) as r:
                rep.retry_after_hint = None
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            # surface the server's pushback hint so poll/drain backoff
            # can honor it (kube/real.py does the same for 429/503)
            rep.retry_after_hint = _retry_after_seconds(e.headers)
            try:
                return e.code, json.loads(e.read().decode() or "{}")
            except ValueError:
                return e.code, {}
        except _TRANSPORT_EXC:
            self.breaker_fail(rep)
            raise


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpuslice-router")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--replica", action="append", default=[],
                    metavar="URL",
                    help="engine replica base URL (repeatable); more "
                         "can join later via POST /v1/replicas")
    ap.add_argument("--poll-interval", type=float, default=0.25,
                    help="seconds between /v1/stats polls per replica")
    ap.add_argument("--stale-after", type=float, default=3.0,
                    help="unpolled seconds before a replica stops "
                         "being routable")
    ap.add_argument("--request-timeout", type=float, default=300.0)
    ap.add_argument("--max-retries", type=int, default=2,
                    help="re-route attempts before any token was "
                         "forwarded (shed/dead replicas)")
    ap.add_argument("--session-ttl", type=float, default=600.0,
                    help="seconds of inactivity before a session "
                         "affinity entry expires")
    ap.add_argument("--migrate-timeout", type=float, default=None,
                    help="seconds each migration hop (session import + "
                         "resume handshake) may take before the next "
                         "survivor / the re-prefill fallback is tried; "
                         "0 disables (hops get the full request "
                         "timeout) (env: "
                         "TPUSLICE_ROUTER_MIGRATE_TIMEOUT; default 15)")
    ap.add_argument("--eject-factor", type=float, default=3.0,
                    help="gray-failure ejection: eject a replica whose "
                         "latency-EWMA p95 exceeds this multiple of "
                         "the fleet median (<= 0 disables)")
    ap.add_argument("--readmit-factor", type=float, default=1.5,
                    help="re-admit an ejected replica once its p95 "
                         "falls back within this multiple of the "
                         "fleet median (hysteresis)")
    ap.add_argument("--hedge-after", type=float, default=0.5,
                    help="seconds before an idempotent stats poll is "
                         "hedged with a second request (first result "
                         "wins; <= 0 disables)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="Prometheus /metrics port (0 = off)")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if not args.replica:
        log.warning("starting with ZERO replicas — add them via "
                    "POST /v1/replicas {\"url\": ...}")
    router = Router(
        replicas=args.replica, host=args.host, port=args.port,
        poll_interval=args.poll_interval, stale_after=args.stale_after,
        request_timeout=args.request_timeout,
        max_retries=args.max_retries, session_ttl=args.session_ttl,
        migrate_timeout=args.migrate_timeout,
        eject_factor=args.eject_factor,
        readmit_factor=args.readmit_factor,
        hedge_after=args.hedge_after,
    ).start()
    if args.metrics_port:
        from instaslice_tpu.metrics.metrics import start_metrics_server

        start_metrics_server(router.metrics, args.metrics_port,
                             host=args.host)
    log.info("routing %d replica(s) on %s", len(router.replicas()),
             router.url)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        router.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
